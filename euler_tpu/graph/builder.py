"""graph.json → partitioned columnar shards.

Replaces the reference's offline converter (euler/tools/generate_euler_data.py:28-51,
json2partdat.py) with a single-pass columnar builder. Input schema is the same
graph.json the reference consumes (tools/test_data/graph.json): nodes have
{id, type, weight, features:[{name, type: dense|sparse|binary, value}]}, edges have
{src, dst, type, weight, features}. Nodes are partitioned by `id % P`, edges by
`src % P` (the reference's graph_partition invariant, optimizer.h:49-86), and an
in-edge adjacency partitioned by `dst % P` is built as well so in-neighbor queries
(node.h:82-112 in-variants) stay shard-local.

Per-shard array layout (see store.py for the query side):

    node_ids u64[N] (sorted), node_types i32[N], node_weights f32[N]
    adj_{t}_indptr i64[N+1], adj_{t}_dst u64[nnz], adj_{t}_w f32[nnz],
        adj_{t}_eidx i64[nnz]           (out-adjacency per edge type, CSR)
    inadj_{t}_* — same, keyed by destination node
    edge_src/edge_dst u64[E], edge_types i32[E], edge_weights f32[E]
    nf_dense_{fid} f32[N, dim]; nf_sparse_{fid}_indptr/_values;
    nf_bin_{fid}_indptr/_values u8     (node features; ef_* for edge features)
    glabel_indptr i64[L+1], glabel_nodes u64 — nodes grouped by graph_label
"""

from __future__ import annotations

import json
import os

import numpy as np

from euler_tpu.graph import format as tformat
from euler_tpu.graph.meta import BINARY, DENSE, SPARSE, FeatureSpec, GraphMeta

GRAPH_LABEL_FEATURE = "graph_label"


def _collect_feature_specs(items: list[dict]) -> dict[str, FeatureSpec]:
    """Scan records and assign deterministic fids per kind (sorted by name)."""
    kinds: dict[str, str] = {}
    dims: dict[str, int] = {}
    for it in items:
        for feat in it.get("features", ()):
            name, kind = feat["name"], feat["type"]
            if kinds.setdefault(name, kind) != kind:
                raise ValueError(f"feature {name!r} has inconsistent kinds")
            v = feat["value"]
            length = len(v) if kind != BINARY else len(str(v).encode())
            dims[name] = max(dims.get(name, 0), length)
    specs: dict[str, FeatureSpec] = {}
    for kind in (DENSE, SPARSE, BINARY):
        names = sorted(n for n, k in kinds.items() if k == kind)
        for fid, name in enumerate(names):
            specs[name] = FeatureSpec(name=name, kind=kind, fid=fid, dim=dims[name])
    return specs


def _feature_arrays(
    items: list[dict], specs: dict[str, FeatureSpec], prefix: str
) -> dict[str, np.ndarray]:
    """Build columnar feature arrays for `items` (already one partition)."""
    n = len(items)
    out: dict[str, np.ndarray] = {}
    by_fid = {(s.kind, s.fid): s for s in specs.values()}
    # index features per item for O(1) lookup
    per_item = [
        {f["name"]: f["value"] for f in it.get("features", ())} for it in items
    ]
    for (kind, fid), spec in sorted(by_fid.items()):
        if kind == DENSE:
            arr = np.zeros((n, spec.dim), dtype=np.float32)
            for i, feats in enumerate(per_item):
                v = feats.get(spec.name)
                if v is not None:
                    arr[i, : len(v)] = v
            out[f"{prefix}_dense_{fid}"] = arr
        elif kind == SPARSE:
            vals, indptr = [], np.zeros(n + 1, dtype=np.int64)
            for i, feats in enumerate(per_item):
                v = feats.get(spec.name) or []
                vals.extend(int(x) for x in v)
                indptr[i + 1] = len(vals)
            out[f"{prefix}_sparse_{fid}_indptr"] = indptr
            out[f"{prefix}_sparse_{fid}_values"] = np.asarray(vals, dtype=np.uint64)
        else:  # binary
            blob, indptr = bytearray(), np.zeros(n + 1, dtype=np.int64)
            for i, feats in enumerate(per_item):
                v = feats.get(spec.name)
                if v is not None:
                    blob.extend(str(v).encode())
                indptr[i + 1] = len(blob)
            out[f"{prefix}_bin_{fid}_indptr"] = indptr
            out[f"{prefix}_bin_{fid}_values"] = np.frombuffer(
                bytes(blob), dtype=np.uint8
            )
    return out


def _csr_adjacency(
    node_ids: np.ndarray,
    key_ids: np.ndarray,
    other_ids: np.ndarray,
    types: np.ndarray,
    weights: np.ndarray,
    eidx: np.ndarray,
    num_edge_types: int,
    tag: str,
) -> dict[str, np.ndarray]:
    """Group edges (columnar) by (key node row, type) into per-type CSRs.

    One vectorized pass: row lookup via searchsorted, then a single
    lexsort by (type, row) emits every per-type CSR slice at once.
    """
    n = len(node_ids)
    out: dict[str, np.ndarray] = {}
    if n == 0 or len(key_ids) == 0:
        for t in range(num_edge_types):
            out[f"{tag}_{t}_indptr"] = np.zeros(n + 1, dtype=np.int64)
            out[f"{tag}_{t}_dst"] = np.zeros(0, dtype=np.uint64)
            out[f"{tag}_{t}_w"] = np.zeros(0, dtype=np.float32)
            out[f"{tag}_{t}_eidx"] = np.zeros(0, dtype=np.int64)
        return out
    pos = np.clip(np.searchsorted(node_ids, key_ids), 0, n - 1)
    rows = np.where(node_ids[pos] == key_ids, pos, -1)
    keep = rows >= 0
    rows, other_ids, types = rows[keep], other_ids[keep], types[keep]
    weights, eidx = weights[keep], eidx[keep]
    perm = np.lexsort((rows, types))
    rows, other_ids = rows[perm], other_ids[perm]
    types, weights, eidx = types[perm], weights[perm], eidx[perm]
    starts = np.searchsorted(types, np.arange(num_edge_types + 1))
    for t in range(num_edge_types):
        s, e = starts[t], starts[t + 1]
        r = rows[s:e]
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(indptr, r + 1, 1)
        out[f"{tag}_{t}_indptr"] = np.cumsum(indptr)
        out[f"{tag}_{t}_dst"] = other_ids[s:e].astype(np.uint64)
        out[f"{tag}_{t}_w"] = weights[s:e].astype(np.float32)
        out[f"{tag}_{t}_eidx"] = eidx[s:e].astype(np.int64)
    return out


def build_partition_arrays(
    nodes: list[dict],
    edges: list[dict],
    in_edges: list[dict],
    node_specs: dict[str, FeatureSpec],
    edge_specs: dict[str, FeatureSpec],
    num_edge_types: int,
    graph_labels: list[str],
) -> dict[str, np.ndarray]:
    """Arrays for one shard. `edges` have src here; `in_edges` have dst here."""
    nodes = sorted(nodes, key=lambda x: int(x["id"]))
    node_ids = np.asarray([int(x["id"]) for x in nodes], dtype=np.uint64)
    arrays: dict[str, np.ndarray] = {
        "node_ids": node_ids,
        "node_types": np.asarray([int(x["type"]) for x in nodes], dtype=np.int32),
        "node_weights": np.asarray(
            [float(x.get("weight", 1.0)) for x in nodes], dtype=np.float32
        ),
        "edge_src": np.asarray([int(e["src"]) for e in edges], dtype=np.uint64),
        "edge_dst": np.asarray([int(e["dst"]) for e in edges], dtype=np.uint64),
        "edge_types": np.asarray([int(e["type"]) for e in edges], dtype=np.int32),
        "edge_weights": np.asarray(
            [float(e.get("weight", 1.0)) for e in edges], dtype=np.float32
        ),
    }
    e_src = arrays["edge_src"]
    e_dst = arrays["edge_dst"]
    e_tt = arrays["edge_types"]
    e_w = arrays["edge_weights"]
    arrays.update(
        _csr_adjacency(
            node_ids, e_src, e_dst, e_tt, e_w,
            np.arange(len(edges), dtype=np.int64), num_edge_types, "adj",
        )
    )
    # in-edges live on dst's shard but their feature rows live on src's shard:
    # eidx is only valid when the edge is also locally owned, else -1
    # (consumers resolve off-shard edge features via (src,dst,type) triples).
    local_row = {id(e): i for i, e in enumerate(edges)}
    in_eidx = np.asarray(
        [local_row.get(id(e), -1) for e in in_edges], dtype=np.int64
    )
    arrays.update(
        _csr_adjacency(
            node_ids,
            np.asarray([int(e["dst"]) for e in in_edges], dtype=np.uint64),
            np.asarray([int(e["src"]) for e in in_edges], dtype=np.uint64),
            np.asarray([int(e["type"]) for e in in_edges], dtype=np.int32),
            np.asarray([float(e.get("weight", 1.0)) for e in in_edges], dtype=np.float32),
            in_eidx,
            num_edge_types,
            "inadj",
        )
    )
    arrays.update(_feature_arrays(nodes, node_specs, "nf"))
    arrays.update(_feature_arrays(edges, edge_specs, "ef"))

    # graph-label grouping (whole-graph / graph-classification path,
    # sample_ops.py:235-237 parity)
    label_nodes: list[list[int]] = [[] for _ in graph_labels]
    label_of = {lab: i for i, lab in enumerate(graph_labels)}
    for nd in nodes:
        for f in nd.get("features", ()):
            if f["name"] == GRAPH_LABEL_FEATURE and f["type"] == BINARY:
                li = label_of.get(str(f["value"]))
                if li is not None:
                    label_nodes[li].append(int(nd["id"]))
    indptr = np.zeros(len(graph_labels) + 1, dtype=np.int64)
    flat: list[int] = []
    for i, ns in enumerate(label_nodes):
        flat.extend(sorted(ns))
        indptr[i + 1] = len(flat)
    arrays["glabel_indptr"] = indptr
    arrays["glabel_nodes"] = np.asarray(flat, dtype=np.uint64)
    return arrays


def build_from_json(
    graph_json: str | dict, num_partitions: int = 1, name: str = "graph"
) -> tuple[GraphMeta, list[dict[str, np.ndarray]]]:
    """Parse graph.json (path or dict) → (meta, per-partition array dicts)."""
    if isinstance(graph_json, str):
        with open(graph_json) as f:
            data = json.load(f)
    else:
        data = graph_json
    nodes, edges = data["nodes"], data["edges"]
    node_specs = _collect_feature_specs(nodes)
    edge_specs = _collect_feature_specs(edges)
    num_node_types = 1 + max((int(n["type"]) for n in nodes), default=-1)
    num_edge_types = 1 + max((int(e["type"]) for e in edges), default=-1)

    labels = sorted(
        {
            str(f["value"])
            for nd in nodes
            for f in nd.get("features", ())
            if f["name"] == GRAPH_LABEL_FEATURE and f["type"] == BINARY
        }
    )

    parts_nodes: list[list[dict]] = [[] for _ in range(num_partitions)]
    parts_edges: list[list[dict]] = [[] for _ in range(num_partitions)]
    parts_in_edges: list[list[dict]] = [[] for _ in range(num_partitions)]
    for nd in nodes:
        parts_nodes[int(nd["id"]) % num_partitions].append(nd)
    for e in edges:
        parts_edges[int(e["src"]) % num_partitions].append(e)
        parts_in_edges[int(e["dst"]) % num_partitions].append(e)

    meta = GraphMeta(
        name=name,
        num_partitions=num_partitions,
        num_node_types=num_node_types,
        num_edge_types=num_edge_types,
        node_features=node_specs,
        edge_features=edge_specs,
        graph_labels=labels,
    )
    shards = []
    for p in range(num_partitions):
        arrays = build_partition_arrays(
            parts_nodes[p],
            parts_edges[p],
            parts_in_edges[p],
            node_specs,
            edge_specs,
            num_edge_types,
            labels,
        )
        nw = np.zeros(num_node_types, dtype=np.float64)
        np.add.at(nw, arrays["node_types"], arrays["node_weights"].astype(np.float64))
        ew = np.zeros(num_edge_types, dtype=np.float64)
        np.add.at(ew, arrays["edge_types"], arrays["edge_weights"].astype(np.float64))
        meta.node_weight_sums.append(nw.tolist())
        meta.edge_weight_sums.append(ew.tolist())
        shards.append(arrays)
    return meta, shards


def convert_json(
    graph_json: str | dict,
    out_dir: str,
    num_partitions: int = 1,
    name: str = "graph",
) -> GraphMeta:
    """graph.json → on-disk tensor dirs: out_dir/part_{p}/ + euler.meta.json."""
    meta, shards = build_from_json(graph_json, num_partitions, name)
    os.makedirs(out_dir, exist_ok=True)
    for p, arrays in enumerate(shards):
        tformat.write_arrays(os.path.join(out_dir, f"part_{p}"), arrays)
    meta.save(out_dir)
    return meta
