"""Per-shard durability: write-ahead log, epoch snapshots, crash recovery.

PR 8 made the graph mutable under live traffic, but every published epoch
lived only in shard process memory — `kill -9` lost every acked mutation.
Euler 2.0's engine persists shards as compacted on-disk blocks it reloads
per shard (PAPER.md, graph engine layer); this module is that durability
layer for the streaming-mutation lane:

- **WAL** (`WriteAheadLog`): every mutation verb the service acks
  (`upsert_nodes` / `upsert_edges` / `delete_edges` / `publish_epoch` —
  `WAL_VERBS`, kept in lockstep with the writer's mutation verbs by
  graftlint's wire-protocol checker) appends one checksummed,
  length-prefixed record reusing the WIRE payload encoding, with its
  idempotency key inside. The record is fsync'd — group-committed across
  concurrent stagers (`EULER_TPU_WAL_FSYNC=batch`, the default), per
  record (`always`), or not at all (`off`) — BEFORE the ack leaves the
  server, so an acked batch is never lost. A torn tail record (crash
  mid-write) fails its length/CRC check and is truncated, never replayed
  partially; everything before it is a valid prefix by construction.
- **Snapshots** (`write_snapshot` / `load_snapshot`): the post-merge
  store's partition arrays serialized as a tensor dir (graph/format.py —
  the same compacted on-disk blocks the loader mmaps), plus the
  applied-idempotency-key window and the WAL position the snapshot
  covers. Written to a temp dir and committed with one atomic rename;
  the previous snapshot is kept as a fallback until the next commit.
  Copy-on-write publishes make this safe off the dispatch path: the
  snapshot serializes an immutable store object while serving continues.
- **Recovery** (`recover`): newest valid snapshot (else the shard's
  source arrays) + replay of the WAL suffix. Mutation records re-stage
  through the same DeltaStore code the live path uses and publish
  records re-merge, so the recovered store is BIT-IDENTICAL to the
  pre-crash published epoch — and the applied-key window is restored
  with it, so writer retries that straddle the crash still apply
  exactly once.

WAL file layout:  [8B magic "EULRWAL1"][u64 base]  then records
Record layout:    [u32 payload_len][u32 crc32(payload)][payload]
`payload` is exactly the wire payload of ``(op, values)`` (the frame
body `wire.encode` builds, minus its 4-byte frame length), so the WAL
speaks the same encoding as the RPC that produced it. `base` is the
LOGICAL offset of the first record — `trim()` drops the prefix a
committed snapshot covers by rewriting the file with a new base, so
snapshot metadata can reference stable logical positions across trims.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import struct
import threading
import time
import zlib

import numpy as np

from euler_tpu.distributed import wire
from euler_tpu.graph import format as tformat

MAGIC = b"EULRWAL1"
_HEADER = struct.Struct("<8sQ")  # magic, base logical offset
_REC = struct.Struct("<II")  # payload_len, crc32

# Load-bearing: every mutation verb the service logs (and recovery
# replays). graftlint's wire-protocol checker asserts this table stays in
# lockstep with the writer's mutation verbs (GraphWriter.WIRE_VERBS minus
# its read-only verbs) — adding a mutation verb on the wire without its
# WAL record type would make that verb silently non-durable.
WAL_VERBS = frozenset({
    "delete_edges",
    "publish_epoch",
    "upsert_edges",
    "upsert_nodes",
})

SNAP_PREFIX = "snap_"
WAL_FILE = "wal.log"

# Term stamping (PR 13 replication) rides the op string the way deadline
# budgets ride the wire op (wire.DEADLINE_PREFIX): a record written by a
# primary at term T stores op "@t:<T>:<op>". The frame layout is
# untouched, and a pre-replication WAL — whose ops carry no envelope —
# unwraps to term 0, so old logs replay unchanged.
TERM_PREFIX = "@t:"


def wrap_term(op: str, term: int) -> str:
    """Envelope `op` with the primary's lease term (0 = no envelope)."""
    return f"{TERM_PREFIX}{int(term)}:{op}" if term > 0 else op


def unwrap_term(op: str) -> tuple[str, int]:
    """(inner op, term) — (op, 0) for pre-replication records."""
    if not op.startswith(TERM_PREFIX):
        return op, 0
    _, term, inner = op.split(":", 2)
    return inner, int(term)


def fsync_mode() -> str:
    """EULER_TPU_WAL_FSYNC: "batch" (default — group commit across
    concurrent stagers), "always" (one fsync per record), "off" (no
    fsync; acked durability then depends on the OS page cache)."""
    mode = os.environ.get("EULER_TPU_WAL_FSYNC", "batch").lower()
    if mode in ("0", "off", "none"):
        return "off"
    if mode in ("always", "every", "2"):
        return "always"
    return "batch"


def snapshot_every() -> int:
    """EULER_TPU_SNAPSHOT_EVERY: snapshot cadence in publishes (default
    4; 0 disables cadence snapshots — the WAL then grows until an
    explicit `snapshot_now`)."""
    return int(os.environ.get("EULER_TPU_SNAPSHOT_EVERY", 4))


def encode_record(op: str, values: list, term: int = 0) -> bytes:
    """One WAL record for (op, values), wire payload encoding inside;
    `term > 0` stamps the writing primary's lease term into the op."""
    if unwrap_term(op)[0] not in WAL_VERBS:
        raise ValueError(f"op {op!r} is not a WAL record type (WAL_VERBS)")
    frame = wire.encode(wrap_term(op, term), values)
    payload = bytes(memoryview(frame)[4:])  # drop the frame length prefix
    return _REC.pack(len(payload), zlib.crc32(payload)) + payload


def decode_record(payload) -> tuple[str, list]:
    """Record payload → (op, values); arrays are copies (no borrow)."""
    return wire.decode(payload)


class WriteAheadLog:
    """Append-only durable log of mutation records for ONE shard.

    Thread-safe: `write()` (buffered, ordered — call it under the same
    lock that orders the staging it describes) and `commit()` (fsync up
    to a write, group-committed) are the two-phase hot path;
    `append()` = write + commit for callers without an external order.
    """

    def __init__(self, path: str, fsync: str | None = None):
        self.path = path
        self.fsync = fsync or fsync_mode()
        self._lock = threading.Lock()  # orders writes + guards offsets
        self._sync_lock = threading.Lock()  # serializes group commits
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "ab")
        if fresh:
            self._f.write(_HEADER.pack(MAGIC, 0))
            self._f.flush()
            self.base = 0
            self._size = 0
        else:
            with open(path, "rb") as f:
                magic, base = _HEADER.unpack(f.read(_HEADER.size))
            if magic != MAGIC:
                raise ValueError(f"{path}: not a WAL file (bad magic)")
            self.base = int(base)
            self._size = os.path.getsize(path) - _HEADER.size
        # group-commit bookkeeping: a commit(seq) returns as soon as ANY
        # fsync covered seq, so N concurrent stagers share one fsync
        self._written_seq = 0
        self._synced_seq = 0
        self.records_written = 0  # telemetry

    # -- append path -----------------------------------------------------

    def write(self, op: str, values: list, term: int = 0) -> tuple[int, int]:
        """Buffered append; returns (seq, end_logical_offset). NOT yet
        durable — call commit(seq) before acking. Callers that need the
        record order to match another structure's mutation order (the
        service's delta staging) hold their ordering lock around this."""
        rec = encode_record(op, values, term)
        with self._lock:
            self._f.write(rec)
            self._f.flush()  # to the OS — fsync is commit()'s job
            self._size += len(rec)
            self._written_seq += 1
            self.records_written += 1
            return self._written_seq, self.base + self._size

    def commit(self, seq: int) -> None:
        """Make every record up to `seq` durable (per the fsync mode).
        Group commit: whoever gets the sync lock fsyncs for everyone
        written so far; later waiters observe coverage and return."""
        if self.fsync == "off":
            return
        with self._sync_lock:
            if self._synced_seq >= seq:
                return  # a concurrent commit already covered this record
            with self._lock:
                target = self._written_seq
                fd = self._f.fileno()
            os.fsync(fd)
            self._synced_seq = target

    def append(self, op: str, values: list, term: int = 0) -> int:
        """write + commit; returns the end logical offset."""
        seq, pos = self.write(op, values, term)
        self.commit(seq)
        return pos

    # -- introspection ---------------------------------------------------

    def tell(self) -> int:
        """Logical end offset (stable across trims)."""
        with self._lock:
            return self.base + self._size

    def size(self) -> int:
        """Physical bytes of un-snapshotted records (the `wal_bytes`
        durability-lag stat)."""
        with self._lock:
            return self._size

    # -- shipping (PR 13 replication) ------------------------------------

    def read_raw(self, from_logical: int, max_bytes: int) -> tuple[bytes, int]:
        """Raw record bytes for the log suffix starting at `from_logical`
        (a logical offset), cut at a record boundary ≤ `max_bytes` (the
        first record always ships whole so progress is guaranteed).
        Returns (bytes, end_logical). Serves only what `write()` already
        flushed — a concurrent half-buffered record is invisible because
        writes land under the lock and flush before releasing it.
        Raises ValueError when `from_logical` predates the base (that
        prefix was trimmed into a snapshot — ship the snapshot instead)."""
        with self._lock:
            if from_logical < self.base:
                raise ValueError(
                    f"logical {from_logical} < base {self.base} (trimmed)"
                )
            end = self.base + self._size
            if from_logical >= end:
                return b"", end
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(_HEADER.size + (from_logical - self.base))
                blob = f.read(end - from_logical)
        # cut at the last whole-record boundary inside max_bytes
        off = 0
        while off + _REC.size <= len(blob):
            (n, _crc) = _REC.unpack_from(blob, off)
            rec_end = off + _REC.size + n
            if rec_end > len(blob):
                break  # only whole records ship
            if off > 0 and rec_end > max_bytes:
                break
            off = rec_end
        return bytes(blob[:off]), from_logical + off

    def crc_range(self, from_logical: int, to_logical: int) -> int:
        """crc32 of the raw bytes in [from_logical, to_logical) — the
        log-continuity handshake: a follower offers the checksum of its
        own tail and the primary compares against the same logical range
        of ITS log. A mismatch means the histories diverged (an
        ex-primary carrying un-replicated records), so the follower must
        rebootstrap from a snapshot instead of appending a suffix onto a
        different prefix. Raises ValueError when the range is outside
        this log (trimmed below, or beyond the end)."""
        with self._lock:
            if (
                from_logical < self.base
                or to_logical > self.base + self._size
                or from_logical > to_logical
            ):
                raise ValueError(
                    f"crc range [{from_logical}, {to_logical}) outside"
                    f" log [{self.base}, {self.base + self._size})"
                )
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(_HEADER.size + (from_logical - self.base))
                blob = f.read(to_logical - from_logical)
        return zlib.crc32(blob) & 0xFFFFFFFF

    def append_raw(self, data: bytes, durable: bool = True) -> int:
        """Append already-encoded records verbatim (a follower applying a
        shipped suffix — the caller validated record integrity by parsing
        first) and fsync them per the fsync mode. Byte-identical appends
        keep every replica's logical offsets interchangeable. Returns the
        new end logical offset. durable=False skips the fsync — a
        pipelined follower streaming a catch-up backlog defers it and
        calls sync() before advancing its reported durable ack."""
        if not data:
            return self.tell()
        with self._lock:
            self._f.write(data)
            self._f.flush()
            self._size += len(data)
            self._written_seq += 1
            self.records_written += 1
            seq, end = self._written_seq, self.base + self._size
        if durable:
            self.commit(seq)
        return end

    def sync(self) -> None:
        """Make everything written so far durable (per the fsync mode) —
        closes a durable=False append_raw window."""
        with self._lock:
            seq = self._written_seq
        self.commit(seq)

    def reset(self, base_logical: int) -> None:
        """Drop every record and restart the log at `base_logical` — a
        follower installing a shipped snapshot starts its (byte-
        interchangeable) log at the snapshot's covered position."""
        with self._sync_lock, self._lock:
            self._f.close()
            tmp = self.path + ".reset"
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(MAGIC, int(base_logical)))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
            self.base = int(base_logical)
            self._size = 0

    # -- trim ------------------------------------------------------------

    def trim(self, upto_logical: int) -> int:
        """Drop records a committed snapshot covers: rewrite the file
        keeping only bytes past `upto_logical`, with a new base, and
        swap it in atomically. Returns bytes dropped. Appends may race —
        both locks are held across the swap, so nothing is lost."""
        with self._sync_lock, self._lock:
            keep_from = upto_logical - self.base
            if keep_from <= 0:
                return 0
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(_HEADER.size + keep_from)
                suffix = f.read()
            tmp = self.path + ".trim"
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(MAGIC, upto_logical))
                f.write(suffix)
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
            self.base = upto_logical
            self._size = len(suffix)
            return keep_from

    # -- at-rest integrity (PR 15 scrubber) ------------------------------

    def verify(self) -> dict:
        """Re-read the log file and CRC-check every record AT REST — the
        scrubber's detection pass for silent bit-rot. In-memory state is
        unaffected by disk rot (records were applied when written), so a
        failure here means a future restart would lose the suffix, not
        that serving is wrong. Returns {"ok", "header_ok", "valid_end",
        "end"}: `valid_end < end` marks the first rotten byte's record
        (safe to trust because `write()` flushes whole records under the
        lock — the at-rest file is always record-complete up to `end`)."""
        with self._lock:
            self._f.flush()
            base, end = self.base, self.base + self._size
            with open(self.path, "rb") as f:
                blob = f.read()
        header_ok = len(blob) >= _HEADER.size
        if header_ok:
            magic, hdr_base = _HEADER.unpack_from(blob, 0)
            header_ok = magic == MAGIC and int(hdr_base) == base
        if not header_ok:
            return {"ok": end == base, "header_ok": False,
                    "valid_end": base, "end": end}
        _, valid = parse_records(blob[_HEADER.size:_HEADER.size
                                      + (end - base)], base)
        return {"ok": valid >= end, "header_ok": True,
                "valid_end": int(valid), "end": int(end)}

    def splice(self, from_logical: int, to_logical: int, data: bytes) -> None:
        """Overwrite the byte range [from_logical, to_logical) with
        `data` (same length, record-validated by the caller) and rewrite
        the file atomically — the at-rest bit-rot REPAIR path. The
        replacement restores bytes only: the records were applied to
        memory when first written, so no replay happens here. Peer bytes
        are safe verbatim because replica logs are byte-interchangeable
        (`append_raw`). Both locks are held across the swap, so racing
        appends land after the preserved suffix and nothing is lost."""
        if len(data) != to_logical - from_logical:
            raise ValueError(
                f"splice data is {len(data)}B for a "
                f"{to_logical - from_logical}B range"
            )
        with self._sync_lock, self._lock:
            if (
                from_logical < self.base
                or to_logical > self.base + self._size
                or from_logical > to_logical
            ):
                raise ValueError(
                    f"splice range [{from_logical}, {to_logical}) outside"
                    f" log [{self.base}, {self.base + self._size})"
                )
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(_HEADER.size)
                blob = f.read(self._size)
            tmp = self.path + ".splice"
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(MAGIC, self.base))
                f.write(blob[: from_logical - self.base])
                f.write(data)
                f.write(blob[to_logical - self.base:])
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")

    def close(self) -> None:
        with self._sync_lock, self._lock:
            try:
                self._f.flush()
                if self.fsync != "off":
                    os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()


def parse_records(
    blob, start_logical: int
) -> tuple[list[tuple[str, list, int, int]], int]:
    """Parse raw record bytes (no file header) starting at logical
    offset `start_logical`. Returns (records, valid_end_logical); each
    record is (op, values, end_logical_offset, term) with the term
    envelope unwrapped (pre-replication records → term 0).

    Stops at the first torn or corrupt record (short header, short
    payload, CRC mismatch, undecodable payload, non-WAL op): everything
    before it is the valid prefix. Shared by `scan` (file replay) and
    the replication follower (validating a shipped suffix before the
    verbatim `append_raw`)."""
    records: list[tuple[str, list, int, int]] = []
    view = memoryview(blob)  # per-record slices stay views, not copies
    off = 0
    valid = 0
    while off + _REC.size <= len(blob):
        n, crc = _REC.unpack_from(view, off)
        start = off + _REC.size
        if start + n > len(blob):
            break  # torn tail: length prefix written, payload cut short
        payload = view[start : start + n]
        if zlib.crc32(payload) != crc:
            break  # corrupt (or a torn length field pointing at garbage)
        try:
            op, values = decode_record(payload)
            op, term = unwrap_term(op)
        except ValueError:
            break  # CRC collision on garbage — still a broken tail
        if op not in WAL_VERBS:
            break
        off = start + n
        valid = off
        records.append((op, values, int(start_logical) + off, term))
    return records, int(start_logical) + valid


def scan(path: str) -> tuple[list[tuple[str, list, int]], int, int]:
    """Parse a WAL file. Returns (records, base, valid_end_logical);
    each record is (op, values, end_logical_offset) — terms, if any,
    already unwrapped (`parse_records` exposes them when needed).

    Stops at the first torn or corrupt record: everything before it is
    the valid prefix, everything from it on is dropped by
    `truncate_torn_tail`. A missing file is an empty log."""
    if not os.path.exists(path):
        return [], 0, 0
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < _HEADER.size:
        return [], 0, 0
    magic, base = _HEADER.unpack_from(blob, 0)
    if magic != MAGIC:
        raise ValueError(f"{path}: not a WAL file (bad magic)")
    records4, valid_end = parse_records(blob[_HEADER.size:], int(base))
    return (
        [(op, values, end) for op, values, end, _term in records4],
        int(base),
        valid_end,
    )


def truncate_torn_tail(path: str) -> int:
    """Cut the file back to its valid record prefix; returns bytes
    dropped (0 when the log is clean)."""
    if not os.path.exists(path):
        return 0
    records, base, valid_end = scan(path)
    keep = _HEADER.size + (valid_end - base)
    size = os.path.getsize(path)
    if size <= keep:
        return 0
    with open(path, "r+b") as f:
        f.truncate(keep)
        f.flush()
        os.fsync(f.fileno())
    return size - keep


# ---------------------------------------------------------------------------
# snapshots
# ---------------------------------------------------------------------------

# Per-file crc32 manifest inside each snapshot dir (PR 15): written
# BEFORE snapshot.json so the commit marker still lands last, covering
# every data file — what the scrubber and the backup archiver verify
# against to catch at-rest bit-rot. Pre-manifest snapshots (older
# clusters) are unverifiable, never quarantined.
CRC_FILE = "crc.json"
CORRUPT_SUFFIX = ".corrupt"


def _crc_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
    return crc & 0xFFFFFFFF


def is_committed_snapshot_name(name: str) -> bool:
    """True for a committed `snap_<epoch>` dir name — excludes `.tmp`
    aborts AND `.corrupt` quarantines (both still carry the prefix)."""
    return name.startswith(SNAP_PREFIX) and name[len(SNAP_PREFIX):].isdigit()


def quarantine_artifact(path: str) -> str | None:
    """Rename a corrupt artifact out of the active set — NEVER delete it
    (forensics; rot is evidence). Returns the quarantine path, unique-
    suffixed when an earlier quarantine of the same name exists."""
    if not os.path.exists(path):
        return None
    dst = path + CORRUPT_SUFFIX
    n = 1
    while os.path.exists(dst):
        dst = f"{path}{CORRUPT_SUFFIX}.{n}"
        n += 1
    os.rename(path, dst)
    return dst


def verify_snapshot(snap_dir: str) -> list[str] | None:
    """At-rest integrity of one committed snapshot dir: every file in
    its crc.json manifest re-hashed. Returns the list of damaged file
    names ([] = clean), or None when the dir predates crc manifests
    (unverifiable — old but not provably corrupt)."""
    try:
        with open(os.path.join(snap_dir, "snapshot.json")) as f:
            json.load(f)
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return ["snapshot.json"]
    crc_path = os.path.join(snap_dir, CRC_FILE)
    if not os.path.exists(crc_path):
        return None
    try:
        with open(crc_path) as f:
            manifest = json.load(f)["files"]
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        return [CRC_FILE]
    bad = []
    for name in sorted(manifest):
        p = os.path.join(snap_dir, name)
        try:
            got = _crc_file(p)
        except OSError:
            bad.append(name)
            continue
        if got != int(manifest[name]):
            bad.append(name)
    return bad


def _applied_blob(applied: "collections.OrderedDict") -> bytearray:
    """Serialize the applied-key window with the wire encoding: mutation
    keys carry True, publish keys carry their recorded [epoch, rows,
    ids, num_nodes] outcome (rows/ids may be None = full-invalidate)."""
    keys, vals = [], []
    for k, v in applied.items():
        keys.append(str(k))
        vals.append(True if v is True else list(v))
    return wire.encode("applied", [keys, vals])


def _applied_from_blob(blob) -> "collections.OrderedDict":
    op, (keys, vals) = wire.decode(memoryview(blob)[4:])
    if op != "applied":
        raise ValueError(f"bad applied blob op {op!r}")
    out: collections.OrderedDict = collections.OrderedDict()
    for k, v in zip(keys, vals):
        out[k] = True if v is True else tuple(v)
    return out


def write_snapshot(
    wal_dir: str,
    epoch: int,
    arrays: dict,
    applied: "collections.OrderedDict",
    wal_pos: int,
) -> str:
    """Write one epoch snapshot and commit it with an atomic rename.

    Layout: `snap_<epoch:012d>/` holding the tensor dir (tensors.bin/
    idx/json), `applied.bin` (wire-encoded idempotency window), and
    `snapshot.json` ({epoch, wal_pos, ...}) written LAST — a dir without
    it is an aborted write and is ignored (and reaped) by recovery.
    Older snapshots beyond the newest two are removed after commit."""
    final = os.path.join(wal_dir, f"{SNAP_PREFIX}{epoch:012d}")
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    # arrays may be memmaps of the source files; materialize on write.
    # fsync: the rename below is the commit point, so every byte must be
    # on disk first — this is also what makes a replica bootstrap's
    # install_snapshot durable BEFORE the ship is acknowledged.
    tformat.write_arrays(
        tmp, {k: np.asarray(v) for k, v in arrays.items()}, fsync=True
    )
    with open(os.path.join(tmp, "applied.bin"), "wb") as f:
        f.write(_applied_blob(applied))
        f.flush()
        os.fsync(f.fileno())
    # per-file crc manifest for the at-rest scrubber, before the commit
    # marker: a dir whose snapshot.json exists always has its manifest
    crcs = {
        name: _crc_file(os.path.join(tmp, name))
        for name in sorted(os.listdir(tmp))
    }
    with open(os.path.join(tmp, CRC_FILE), "w") as f:
        json.dump({"version": 1, "files": crcs}, f)
        f.flush()
        os.fsync(f.fileno())
    meta = {"version": 1, "epoch": int(epoch), "wal_pos": int(wal_pos),
            "ts": time.time()}
    with open(os.path.join(tmp, "snapshot.json"), "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    if os.path.exists(final):
        shutil.rmtree(final)
    os.replace(tmp, final)
    dfd = os.open(wal_dir, os.O_RDONLY)
    try:
        os.fsync(dfd)  # the rename itself must survive power loss
    finally:
        os.close(dfd)
    # keep the newest two committed snapshots (fallback), reap the rest;
    # quarantined `.corrupt` dirs never count against the retained-good
    # budget and are never reaped (evidence)
    snaps = sorted(
        n for n in os.listdir(wal_dir) if is_committed_snapshot_name(n)
    )
    for name in snaps[:-2]:
        shutil.rmtree(os.path.join(wal_dir, name), ignore_errors=True)
    for name in os.listdir(wal_dir):
        if name.endswith(".tmp"):
            shutil.rmtree(os.path.join(wal_dir, name), ignore_errors=True)
    return final


def load_snapshot(
    wal_dir: str,
    min_wal_pos: int = 0,
    quarantine: bool = False,
    report: dict | None = None,
):
    """Newest VALID snapshot as (epoch, arrays, applied, wal_pos), or
    None. Snapshots whose `wal_pos` predates `min_wal_pos` (the WAL's
    base — their replay suffix was already trimmed away) are unusable
    and skipped; a corrupt newest snapshot falls back to the previous.

    `quarantine=True` (recovery's mode) renames a corrupt dir to
    `snap_<epoch>.corrupt` instead of leaving it in place — otherwise
    the keep-2 GC counts the corpse against the retained-GOOD budget and
    can reap the only loadable fallback. Read-only callers (snapshot
    shipping) keep the default and never mutate the dir. `report`, when
    given, collects the quarantined names under "snapshots_quarantined"."""
    if not os.path.isdir(wal_dir):
        return None
    snaps = sorted(
        (n for n in os.listdir(wal_dir) if is_committed_snapshot_name(n)),
        reverse=True,
    )
    for name in snaps:
        d = os.path.join(wal_dir, name)
        try:
            with open(os.path.join(d, "snapshot.json")) as f:
                meta = json.load(f)
            if int(meta["wal_pos"]) < min_wal_pos:
                continue
            arrays = tformat.read_arrays(d, mmap=False)
            with open(os.path.join(d, "applied.bin"), "rb") as f:
                applied = _applied_from_blob(f.read())
            return int(meta["epoch"]), arrays, applied, int(meta["wal_pos"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            # aborted/corrupt snapshot: fall back to an older one
            if quarantine:
                q = quarantine_artifact(d)
                if report is not None and q is not None:
                    report.setdefault("snapshots_quarantined", []).append(
                        os.path.basename(q)
                    )
            continue
    return None


# ---------------------------------------------------------------------------
# recovery
# ---------------------------------------------------------------------------


def stage_record(delta, op: str, a: list) -> int:
    """Stage one WAL mutation record into a DeltaStore — the SAME
    argument mapping the service's dispatch uses, so replay and the live
    path can never diverge. `a` includes the idempotency key at a[0]."""
    args = a[1:]
    if op == "upsert_nodes":
        return delta.stage_nodes(
            args[0], args[1], args[2], args[3] or [], args[4]
        )
    if op == "upsert_edges":
        return delta.stage_edges(*args[:8])
    if op == "delete_edges":
        return delta.stage_edge_deletes(*args[:6])
    raise ValueError(f"op {op!r} is not a stageable WAL record")


class RecoveredShard:
    """What `recover` hands back to the service: the restored store, the
    staged-but-unpublished delta (pending, invisible — exactly as it was
    pre-crash), the applied-key window, the reopened WAL, and a report."""

    def __init__(self, store, delta, applied, wal_log, report):
        self.store = store
        self.delta = delta
        self.applied = applied
        self.wal = wal_log
        self.report = report


def recover(
    meta,
    part: int,
    wal_dir: str,
    base_store,
    applied_keys_max: int = 4096,
    publish_result_cap: int = 65536,
) -> RecoveredShard:
    """Restore one shard from its WAL dir.

    newest valid snapshot (else `base_store`'s arrays) + replay of the
    WAL suffix: mutation records re-stage (skipping keys the window
    already applied — a record fsync'd right before a lost ack), publish
    records re-merge. Deterministic merge + preserved record order ⇒ the
    result is bit-identical to the pre-crash state, applied-key window
    included. A torn tail is truncated before replay, never partially
    applied. When there is nothing to recover (no snapshot, empty WAL)
    the provided `base_store` is returned untouched (native engines keep
    serving natively)."""
    from euler_tpu.graph.delta import DeltaStore
    from euler_tpu.graph.store import GraphStore

    t0 = time.perf_counter()
    os.makedirs(wal_dir, exist_ok=True)
    path = os.path.join(wal_dir, WAL_FILE)
    torn = truncate_torn_tail(path)
    records, base, _ = scan(path)
    quar: dict = {}
    snap = load_snapshot(wal_dir, min_wal_pos=base, quarantine=True,
                         report=quar)
    applied: collections.OrderedDict = collections.OrderedDict()
    if snap is None:
        if base > 0:
            raise RuntimeError(
                f"{wal_dir}: WAL base {base} > 0 but no usable snapshot —"
                " records before the base were trimmed away; restore a"
                " snapshot or rebuild the shard from source"
            )
        store = base_store
        snap_epoch = None
    else:
        snap_epoch, arrays, applied, snap_pos = snap
        store = GraphStore(meta, arrays, part)
        store.graph_epoch = snap_epoch
        # replay only records past the snapshot's coverage
        records = [r for r in records if r[2] > snap_pos]
    delta = None
    replayed = publishes = 0
    for op, a, _end in records:
        if op == "publish_epoch":
            key = a[0] if a else None
            if key is not None and f"pub:{key}" in applied:
                continue
            d, delta = delta, None
            if d is None or d.empty:
                result = (
                    int(store.graph_epoch),
                    np.empty(0, np.int64),
                    np.empty(0, np.uint64),
                    int(store.num_nodes),
                )
            else:
                store, rows, ids = store.merge_delta(d)
                if len(rows) + len(ids) > publish_result_cap:
                    rows = ids = None
                result = (
                    int(store.graph_epoch),
                    rows,
                    ids,
                    int(store.num_nodes),
                )
            publishes += 1
            if key is not None:
                applied[f"pub:{key}"] = result
        else:
            key = str(a[0])
            if key in applied:
                continue  # durable record of a batch acked twice: once
            if delta is None:
                # replay must accept what the live path accepted — the
                # bound was enforced at staging time, not here
                delta = DeltaStore(part, meta.num_partitions, max_rows=2**62)
            stage_record(delta, op, a)
            applied[key] = True
            replayed += 1
        while len(applied) > applied_keys_max:
            applied.popitem(last=False)
    wal_log = WriteAheadLog(path)
    report = {
        "recovered": bool(snap is not None or records or torn),
        "snapshot_epoch": snap_epoch,
        "records_replayed": replayed,
        "publishes_replayed": publishes,
        "torn_bytes_dropped": int(torn),
        "snapshots_quarantined": quar.get("snapshots_quarantined", []),
        "recovery_ms": round((time.perf_counter() - t0) * 1e3, 3),
        "graph_epoch": int(getattr(store, "graph_epoch", 0)),
        "pending_rows": 0 if delta is None else delta.pending()["rows"],
    }
    return RecoveredShard(store, delta, applied, wal_log, report)
