"""Tensor-dir binary format shared between the Python store and the C++ engine.

A *tensor dir* is a directory holding:

    tensors.bin   — concatenation of raw little-endian array buffers, each
                    64-byte aligned so the C++ engine can mmap + cast in place.
    tensors.idx   — binary index: magic, count, then per array
                    (name, dtype code, ndim, shape, offset, nbytes).
    tensors.json  — the same index as JSON, for debuggability.

This plays the role of the reference's partitioned binary Node/ Edge/ record
files (euler/core/graph/graph_builder.cc:57-120) but is columnar rather than
record-oriented: the store mmaps whole arrays instead of deserializing
per-record, which is what lets a TPU-VM host load a multi-GB shard in seconds
and serve vectorized batch queries with zero parsing.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

MAGIC = b"EULRTPU1"
ALIGN = 64

# stable dtype codes shared with cpp/graph_engine.cc
_DTYPE_CODES = {
    np.dtype(np.uint8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint64): 4,
    np.dtype(np.float32): 5,
    np.dtype(np.float64): 6,
    np.dtype(np.uint32): 7,
}
try:  # bfloat16 rides the wire for weighted lean minibatches; the C++
    # engine never stores it, so the code is wire-only
    import ml_dtypes

    _DTYPE_CODES[np.dtype(ml_dtypes.bfloat16)] = 8
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def _align(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def write_arrays(
    path: str, arrays: dict[str, np.ndarray], fsync: bool = False
) -> None:
    """Write `arrays` as a tensor dir at `path` (created if needed).

    `fsync=True` flushes every file to stable storage before returning —
    required when the tensor dir is part of a durability commit (WAL
    snapshots, replica bootstrap): the caller's rename is only a commit
    point if the renamed bytes are already on disk."""
    os.makedirs(path, exist_ok=True)
    index = []
    offset = 0
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {arr.dtype} for array {name!r}")
        index.append(
            {
                "name": name,
                "dtype": str(arr.dtype),
                "code": _DTYPE_CODES[arr.dtype],
                "shape": list(arr.shape),
                "offset": offset,
                "nbytes": arr.nbytes,
            }
        )
        offset = _align(offset + arr.nbytes)

    with open(os.path.join(path, "tensors.bin"), "wb") as f:
        for meta, (name, arr) in zip(index, arrays.items()):
            f.seek(meta["offset"])
            f.write(np.ascontiguousarray(arr).tobytes())
        if fsync:
            f.flush()
            os.fsync(f.fileno())

    with open(os.path.join(path, "tensors.idx"), "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<q", len(index)))
        for meta in index:
            name_b = meta["name"].encode()
            f.write(struct.pack("<i", len(name_b)))
            f.write(name_b)
            f.write(struct.pack("<BB", meta["code"], len(meta["shape"])))
            for d in meta["shape"]:
                f.write(struct.pack("<q", d))
            f.write(struct.pack("<qq", meta["offset"], meta["nbytes"]))
        if fsync:
            f.flush()
            os.fsync(f.fileno())

    with open(os.path.join(path, "tensors.json"), "w") as f:
        json.dump({"version": 1, "arrays": index}, f, indent=1)
        if fsync:
            f.flush()
            os.fsync(f.fileno())


def read_arrays(path: str, mmap: bool = True) -> dict[str, np.ndarray]:
    """Read a tensor dir into {name: ndarray}; memory-maps by default."""
    with open(os.path.join(path, "tensors.json")) as f:
        index = json.load(f)["arrays"]
    bin_path = os.path.join(path, "tensors.bin")
    out: dict[str, np.ndarray] = {}
    if mmap:
        buf = np.memmap(bin_path, dtype=np.uint8, mode="r")
    else:
        buf = np.fromfile(bin_path, dtype=np.uint8)
    for meta in index:
        dt = np.dtype(meta["dtype"])
        raw = buf[meta["offset"] : meta["offset"] + meta["nbytes"]]
        out[meta["name"]] = raw.view(dt).reshape(meta["shape"])
    return out
