from euler_tpu.graph.builder import build_from_json, convert_json  # noqa: F401
from euler_tpu.graph.format import read_arrays, write_arrays  # noqa: F401
from euler_tpu.graph.meta import BINARY, DENSE, SPARSE, FeatureSpec, GraphMeta  # noqa: F401
from euler_tpu.graph.store import DEFAULT_ID, Graph, GraphStore  # noqa: F401
from euler_tpu.graph.backends import open_graph, register_backend  # noqa: F401
