"""ctypes binding for the native C++ graph engine (cpp/graph_engine.cc).

Builds the shared library on demand (g++, cached next to the source) and
exposes `NativeGraphStore`, a GraphStore drop-in whose hot queries (global
sampling, neighbor sampling, dense features, walks) run in C++ over mmapped
shard files; everything else falls back to the numpy store.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from euler_tpu.graph.meta import GraphMeta
from euler_tpu.graph.store import GraphStore

_CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp")
_SO_PATH = os.path.abspath(os.path.join(_CPP_DIR, "libeuler_tpu_engine.so"))
_SRC_PATH = os.path.abspath(os.path.join(_CPP_DIR, "graph_engine.cc"))

_lib = None


def build_engine(force: bool = False) -> str:
    """Compile the engine .so if missing or stale; returns its path."""
    if (
        not force
        and os.path.exists(_SO_PATH)
        and os.path.getmtime(_SO_PATH) >= os.path.getmtime(_SRC_PATH)
    ):
        return _SO_PATH
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC_PATH,
        "-o",
        _SO_PATH,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return _SO_PATH


def _u64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _types_arr(edge_types):
    return np.ascontiguousarray(
        [] if edge_types is None else list(edge_types), dtype=np.int32
    )


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(build_engine())
    c = ctypes
    u64p, i64p = c.POINTER(c.c_uint64), c.POINTER(c.c_int64)
    i32p, f32p, u8p = (
        c.POINTER(c.c_int32),
        c.POINTER(c.c_float),
        c.POINTER(c.c_uint8),
    )
    lib.etpu_load.restype = c.c_void_p
    lib.etpu_load.argtypes = [c.c_char_p, c.c_int64, c.c_int64]
    lib.etpu_free.argtypes = [c.c_void_p]
    lib.etpu_num_nodes.restype = c.c_int64
    lib.etpu_num_nodes.argtypes = [c.c_void_p]
    lib.etpu_num_edges.restype = c.c_int64
    lib.etpu_num_edges.argtypes = [c.c_void_p]
    lib.etpu_lookup.argtypes = [c.c_void_p, u64p, c.c_int64, i64p]
    lib.etpu_sample_node.argtypes = [
        c.c_void_p, c.c_int64, c.c_int32, c.c_uint64, u64p,
    ]
    lib.etpu_sample_edge.argtypes = [
        c.c_void_p, c.c_int64, c.c_int32, c.c_uint64, u64p,
    ]
    lib.etpu_sample_neighbor.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint64, u64p, f32p, i32p, u8p, i64p,
    ]
    lib.etpu_get_dense.argtypes = [
        c.c_void_p, u64p, c.c_int64, c.c_int64, c.c_int64, f32p,
    ]
    lib.etpu_random_walk.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint64, u64p,
    ]
    lib.etpu_sample_fanout.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, i64p, c.c_int64,
        c.c_uint64, u64p, i64p, f32p, i32p, u8p,
    ]
    lib.etpu_get_dense_rows.argtypes = [
        c.c_void_p, i64p, c.c_int64, c.c_int64, c.c_int64, f32p,
    ]
    lib.etpu_stats.argtypes = [c.c_void_p, u64p]
    lib.etpu_reset_stats.argtypes = [c.c_void_p]
    lib.etpu_degree_sum.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_uint8, i64p,
    ]
    lib.etpu_full_neighbor.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint8, c.c_int32, u64p, f32p, i32p, u8p, i64p,
    ]
    lib.etpu_varlen_lens.argtypes = [
        c.c_void_p, i64p, c.c_int64, c.c_uint8, c.c_int32, c.c_int64, i64p,
    ]
    lib.etpu_varlen_gather_u64.argtypes = [
        c.c_void_p, i64p, c.c_int64, c.c_uint8, c.c_int32, c.c_int64,
        c.c_int64, u64p, u8p,
    ]
    lib.etpu_varlen_gather_u8.argtypes = [
        c.c_void_p, i64p, c.c_int64, c.c_uint8, c.c_int32, c.c_int64,
        c.c_int64, u8p,
    ]
    lib.etpu_layerwise.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint64, u64p, f32p, u8p,
    ]
    lib.etpu_sample_neighbor_dir.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint8, c.c_uint64, u64p, f32p, i32p, u8p, i64p,
    ]
    lib.etpu_sample_neighbor_rows.argtypes = [
        c.c_void_p, u64p, c.c_int64, i32p, c.c_int64, c.c_int64,
        c.c_uint64, u64p, u8p, i64p,
    ]
    _lib = lib
    return lib


# per-op counters exported by the engine (Op enum order in graph_engine.cc)
STAT_OPS = (
    "lookup",
    "sample_node",
    "sample_edge",
    "sample_neighbor",
    "get_dense",
    "random_walk",
    "sample_fanout",
    "full_neighbor",
    "degree_sum",
    "varlen_feature",
    "layerwise",
)


def engine_available() -> bool:
    try:
        _load_lib()
        return True
    except Exception:
        return False


class NativeGraphStore(GraphStore):
    """GraphStore whose hot paths run in the C++ engine.

    Loads the same on-disk tensor dir twice: mmapped numpy views (for the
    cold paths and feature metadata) + the C++ store (hot queries).
    """

    def __init__(self, meta: GraphMeta, arrays, part: int, directory: str):
        super().__init__(meta, arrays, part)
        lib = _load_lib()
        self._lib = lib
        self._h = lib.etpu_load(
            directory.encode(), meta.num_node_types, meta.num_edge_types
        )
        if not self._h:
            raise RuntimeError(f"native engine failed to load {directory}")

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.etpu_free(self._h)
            self._h = None

    # -- hot paths -------------------------------------------------------

    def _seed(self, rng) -> int:
        if rng is None:
            rng = np.random.default_rng()
        return int(rng.integers(0, 2**63 - 1))

    def lookup(self, ids):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        rows = np.empty(len(ids), dtype=np.int64)
        self._lib.etpu_lookup(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            len(ids),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return rows

    def sample_node(self, count, node_type=-1, rng=None):
        out = np.empty(count, dtype=np.uint64)
        self._lib.etpu_sample_node(
            ctypes.c_void_p(self._h),
            count,
            ctypes.c_int32(node_type),
            ctypes.c_uint64(self._seed(rng)),
            _u64p(out),
        )
        return out

    def sample_edge(self, count, edge_type=-1, rng=None):
        out = np.empty((count, 3), dtype=np.uint64)
        self._lib.etpu_sample_edge(
            ctypes.c_void_p(self._h),
            count,
            ctypes.c_int32(edge_type),
            ctypes.c_uint64(self._seed(rng)),
            _u64p(out),
        )
        return out

    def sample_neighbor(self, ids, edge_types=None, count=10, rng=None, in_edges=False):
        if in_edges and not self.inadj:  # no in-CSRs on this shard
            return super().sample_neighbor(ids, edge_types, count, rng, in_edges)
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        n = len(ids)
        types = _types_arr(edge_types)
        nbr = np.empty((n, count), dtype=np.uint64)
        w = np.empty((n, count), dtype=np.float32)
        tt = np.empty((n, count), dtype=np.int32)
        mask = np.empty((n, count), dtype=np.uint8)
        eidx = np.empty((n, count), dtype=np.int64)
        self._lib.etpu_sample_neighbor_dir(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            n,
            _i32p(types),
            len(types),
            count,
            ctypes.c_uint8(1 if in_edges else 0),
            ctypes.c_uint64(self._seed(rng)),
            _u64p(nbr),
            _f32p(w),
            _i32p(tt),
            _u8p(mask),
            _i64p(eidx),
        )
        return nbr, w, tt, mask.astype(bool), eidx

    def sample_neighbor_rows(self, ids, edge_types=None, count=10, rng=None):
        """Lean leaf draw: (nbr, mask, local_rows) with rows pre-resolved
        from the engine's load-time dst_row cache (-1 for off-shard dsts).
        No weight/type/edge-id outputs — the distributed lean fanout never
        needs them and they dominate the coordinator's byte-shuffling."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        n = len(ids)
        types = _types_arr(edge_types)
        nbr = np.empty((n, count), dtype=np.uint64)
        mask = np.empty((n, count), dtype=np.uint8)
        rows = np.empty((n, count), dtype=np.int64)
        self._lib.etpu_sample_neighbor_rows(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            n,
            _i32p(types),
            len(types),
            count,
            ctypes.c_uint64(self._seed(rng)),
            _u64p(nbr),
            _u8p(mask),
            _i64p(rows),
        )
        return nbr, mask.astype(bool), rows

    def degree_sum(self, ids, edge_types=None, in_edges=False):
        if in_edges and not self.inadj:
            return super().degree_sum(ids, edge_types, in_edges)
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        types = _types_arr(edge_types)
        out = np.empty(len(ids), dtype=np.int64)
        self._lib.etpu_degree_sum(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            len(ids),
            _i32p(types),
            len(types),
            ctypes.c_uint8(1 if in_edges else 0),
            _i64p(out),
        )
        return out

    def get_full_neighbor(
        self, ids, edge_types=None, max_degree=None, in_edges=False, sort_by=None
    ):
        """Padded full adjacency served from the engine (node.h:82-112).

        sort_by: None (storage order) | 'id' | 'weight' (desc); sorting
        happens per row inside the C++ kernel.
        """
        if in_edges and not self.inadj:
            return super().get_full_neighbor(
                ids, edge_types, max_degree, in_edges, sort_by
            )
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        n = len(ids)
        if max_degree is None:
            degs = self.degree_sum(ids, edge_types, in_edges)
            cap = int(degs.max(initial=0))
        else:
            cap = int(max_degree)
        cap = max(cap, 1)
        types = _types_arr(edge_types)
        sort_mode = {None: 0, "id": 1, "weight": 2}[sort_by]
        nbr = np.empty((n, cap), dtype=np.uint64)
        w = np.empty((n, cap), dtype=np.float32)
        tt = np.empty((n, cap), dtype=np.int32)
        mask = np.empty((n, cap), dtype=np.uint8)
        eidx = np.empty((n, cap), dtype=np.int64)
        self._lib.etpu_full_neighbor(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            n,
            _i32p(types),
            len(types),
            cap,
            ctypes.c_uint8(1 if in_edges else 0),
            ctypes.c_int32(sort_mode),
            _u64p(nbr),
            _f32p(w),
            _i32p(tt),
            _u8p(mask),
            _i64p(eidx),
        )
        return nbr, w, tt, mask.astype(bool), eidx

    def sample_neighbor_layerwise(
        self, batch_ids, edge_types=None, count=128, rng=None
    ):
        """LADIES-style layer sampling in one engine call."""
        batch_ids = np.ascontiguousarray(batch_ids, dtype=np.uint64)
        n = len(batch_ids)
        types = _types_arr(edge_types)
        layer = np.empty(count, dtype=np.uint64)
        adj = np.empty((n, count), dtype=np.float32)
        lmask = np.empty(count, dtype=np.uint8)
        self._lib.etpu_layerwise(
            ctypes.c_void_p(self._h),
            _u64p(batch_ids),
            n,
            _i32p(types),
            len(types),
            count,
            ctypes.c_uint64(self._seed(rng)),
            _u64p(layer),
            _f32p(adj),
            _u8p(lmask),
        )
        return layer, adj, lmask.astype(bool)

    # -- variable-length features (sparse u64 / binary bytes) ------------

    def _varlen_lens(self, rows, node: bool, kind: int, fid: int):
        rows = np.ascontiguousarray(rows, dtype=np.int64)
        lens = np.empty(len(rows), dtype=np.int64)
        self._lib.etpu_varlen_lens(
            ctypes.c_void_p(self._h),
            _i64p(rows),
            len(rows),
            ctypes.c_uint8(1 if node else 0),
            ctypes.c_int32(kind),
            fid,
            _i64p(lens),
        )
        return lens

    def _varlen_by_rows(self, rows, names, kind, node: bool, max_len=None):
        from euler_tpu.graph.store import SPARSE

        if kind != SPARSE:  # binary handled by get_*_binary_feature below
            return super()._varlen_by_rows(rows, names, kind, node, max_len)
        rows = np.ascontiguousarray(rows, dtype=np.int64)
        out = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=node)
            lens = self._varlen_lens(rows, node, 0, spec.fid)
            cap = int(max_len) if max_len else max(int(lens.max(initial=0)), 1)
            vals = np.empty((len(rows), cap), dtype=np.uint64)
            mask = np.empty((len(rows), cap), dtype=np.uint8)
            self._lib.etpu_varlen_gather_u64(
                ctypes.c_void_p(self._h),
                _i64p(rows),
                len(rows),
                ctypes.c_uint8(1 if node else 0),
                ctypes.c_int32(0),
                spec.fid,
                cap,
                _u64p(vals),
                _u8p(mask),
            )
            out.append((vals, mask.astype(bool)))
        return out

    def _binary_by_rows(self, rows, names, node: bool):
        rows = np.ascontiguousarray(rows, dtype=np.int64)
        out = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=node)
            lens = self._varlen_lens(rows, node, 1, spec.fid)
            cap = max(int(lens.max(initial=0)), 1)
            vals = np.empty((len(rows), cap), dtype=np.uint8)
            self._lib.etpu_varlen_gather_u8(
                ctypes.c_void_p(self._h),
                _i64p(rows),
                len(rows),
                ctypes.c_uint8(1 if node else 0),
                ctypes.c_int32(1),
                spec.fid,
                cap,
                _u8p(vals),
            )
            out.append(
                [bytes(vals[i, : lens[i]]) for i in range(len(rows))]
            )
        return out

    def get_binary_feature(self, ids, names):
        return self._binary_by_rows(self.lookup(ids), names, node=True)

    def get_edge_binary_feature(self, edge_ids, names):
        return self._binary_by_rows(self._edge_rows(edge_ids), names, node=False)

    def get_dense_feature(self, ids, names):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        cols = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=True)
            out = np.empty((len(ids), spec.dim), dtype=np.float32)
            self._lib.etpu_get_dense(
                ctypes.c_void_p(self._h),
                _u64p(ids),
                len(ids),
                spec.fid,
                spec.dim,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            )
            cols.append(out)
        return (
            np.concatenate(cols, axis=1)
            if cols
            else np.zeros((len(ids), 0), np.float32)
        )

    def fanout_with_rows(self, ids, edge_types, counts, rng=None):
        """Fused multi-hop fanout in one engine call.

        Returns (hop_ids, hop_w, hop_tt, hop_mask, hop_rows) — lists over
        hops 0..len(counts), hop i flat with n*prod(counts[:i]) entries.
        hop_rows are local store rows (-1 invalid), ready for the device
        feature cache without a second lookup pass.
        """
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        n = len(ids)
        types = _types_arr(edge_types)
        counts_arr = np.ascontiguousarray(counts, dtype=np.int64)
        widths = [n]
        for c in counts:
            widths.append(widths[-1] * int(c))
        total = int(np.sum(widths))
        ids_out = np.empty(total, dtype=np.uint64)
        rows_out = np.empty(total, dtype=np.int64)
        w_out = np.empty(total, dtype=np.float32)
        tt_out = np.empty(total, dtype=np.int32)
        mask_out = np.empty(total, dtype=np.uint8)
        ct = ctypes
        self._lib.etpu_sample_fanout(
            ct.c_void_p(self._h),
            _u64p(ids),
            n,
            types.ctypes.data_as(ct.POINTER(ct.c_int32)),
            len(types),
            counts_arr.ctypes.data_as(ct.POINTER(ct.c_int64)),
            len(counts),
            ct.c_uint64(self._seed(rng)),
            _u64p(ids_out),
            rows_out.ctypes.data_as(ct.POINTER(ct.c_int64)),
            w_out.ctypes.data_as(ct.POINTER(ct.c_float)),
            tt_out.ctypes.data_as(ct.POINTER(ct.c_int32)),
            mask_out.ctypes.data_as(ct.POINTER(ct.c_uint8)),
        )
        from euler_tpu.graph.store import split_hops

        ids_h, w_h, tt_h, mask_h, rows_h = split_hops(
            n, counts, ids_out, w_out, tt_out, mask_out, rows_out
        )
        return (
            ids_h,
            w_h,
            tt_h,
            [m.astype(bool) for m in mask_h],
            rows_h,
        )

    def get_dense_by_rows(self, rows, names):
        """Dense features by pre-resolved rows (-1 → zeros); skips lookup."""
        rows = np.ascontiguousarray(rows, dtype=np.int64)
        cols = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=True)
            out = np.empty((len(rows), spec.dim), dtype=np.float32)
            self._lib.etpu_get_dense_rows(
                ctypes.c_void_p(self._h),
                rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(rows),
                spec.fid,
                spec.dim,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            )
            cols.append(out)
        return (
            np.concatenate(cols, axis=1)
            if cols
            else np.zeros((len(rows), 0), np.float32)
        )

    def op_stats(self) -> dict:
        """Per-op (calls, total_ms) timing counters from the engine."""
        out = np.zeros(2 * len(STAT_OPS), dtype=np.uint64)
        self._lib.etpu_stats(
            ctypes.c_void_p(self._h),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        k = len(STAT_OPS)
        return {
            name: {"calls": int(out[i]), "ms": float(out[k + i]) / 1e6}
            for i, name in enumerate(STAT_OPS)
        }

    def reset_op_stats(self):
        self._lib.etpu_reset_stats(ctypes.c_void_p(self._h))

    def random_walk(self, ids, edge_types=None, walk_len=3, p=1.0, q=1.0, rng=None):
        if p != 1.0 or q != 1.0:  # node2vec bias → numpy path
            return super().random_walk(ids, edge_types, walk_len, p, q, rng)
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        types = _types_arr(edge_types)
        out = np.empty((len(ids), walk_len + 1), dtype=np.uint64)
        self._lib.etpu_random_walk(
            ctypes.c_void_p(self._h),
            _u64p(ids),
            len(ids),
            types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(types),
            walk_len,
            ctypes.c_uint64(self._seed(rng)),
            _u64p(out),
        )
        return out
