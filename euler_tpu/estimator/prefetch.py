"""Host-side batch prefetching: overlap graph sampling with device compute.

The reference hides sampling latency with async TF ops on a client thread
pool (query_proxy.cc:205-256); the TPU equivalent is a producer thread (or
pool) keeping a bounded queue of ready MiniBatches ahead of the device step.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable


class Prefetcher:
    """Wraps batch_fn() in N producer threads + a bounded queue.

    With device_put=True, workers also stage each batch onto the device, so
    host→device transfers overlap the previous step's compute instead of
    serializing with it in the training loop.
    """

    def __init__(
        self,
        batch_fn: Callable[[], tuple],
        depth: int = 4,
        workers: int = 2,
        device_put: bool = False,
    ):
        self.batch_fn = batch_fn
        self.device_put = device_put
        self.q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._produce, daemon=True)
            for _ in range(workers)
        ]
        self._error = None
        for t in self._threads:
            t.start()

    def _produce(self):
        while not self._stop.is_set():
            try:
                item = self.batch_fn()
                if self.device_put:
                    import jax

                    item = jax.device_put(item)
            except Exception as e:  # surface producer errors to the consumer
                self._error = e
                self._stop.set()
                break
            while not self._stop.is_set():
                try:
                    self.q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __call__(self) -> tuple:
        while True:
            if self._error is not None:
                raise self._error
            try:
                return self.q.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set() and self._error is None:
                    raise RuntimeError("prefetcher stopped")

    def close(self, timeout_s: float = 5.0):
        """Stop producers and JOIN their threads (bounded).

        The one-shot drain the old close() did raced its own workers: a
        worker blocked in `q.put` could publish one more (stale) batch
        into the just-drained queue after close() returned — a later
        consumer of the same queue object would read a batch from a
        supposedly-dead prefetcher. Draining *until the workers are
        actually joined* closes that window; workers stuck in a slow
        batch_fn (e.g. an RPC riding a dead peer's timeout) are given
        `timeout_s` and then abandoned — they are daemon threads and the
        final drain still empties whatever they managed to publish."""
        self._stop.set()
        deadline = time.monotonic() + timeout_s
        alive = [t for t in self._threads if t.is_alive()]
        while alive and time.monotonic() < deadline:
            self._drain()  # unblock workers waiting in q.put
            for t in alive:
                t.join(timeout=0.05)
            alive = [t for t in alive if t.is_alive()]
        self._drain()

    def _drain(self):
        while True:
            try:
                self.q.get_nowait()
            except queue.Empty:
                return
