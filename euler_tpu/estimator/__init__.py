from euler_tpu.estimator.estimator import (  # noqa: F401
    Estimator,
    EstimatorConfig,
    edge_batches,
    id_batches,
    make_optimizer,
    node_batches,
    pipelined_batches,
    read_sample_ids,
    sample_file_batches,
    stack_batches,
    unsupervised_batches,
)
from euler_tpu.estimator.feature_cache import (  # noqa: F401
    DeviceFeatureCache,
    ResidualFetchRing,
)
