"""HBM-resident node feature cache.

The reference fetches dense features from the graph engine per batch and
ships them through the TF op boundary (feature_ops.py, get_dense_feature
kernels). On TPU the equivalent boundary — host→device transfer — is the
throughput ceiling: a 2-hop fanout batch carries ~B·k1·k2·F floats. The
TPU-native answer is to load the dense feature table into device HBM once
and ship only int32 row indices per batch; the gather runs on device inside
the jitted step, where XLA fuses it with the first layer's matmul.

Pair with DataFlow(feature_mode="rows"): hop feature slots then hold int32
rows into this cache's table (row 0 = zero/padding row), and
`hydrate(batch)` — called inside jit by the Estimator — turns them back
into dense per-hop matrices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from euler_tpu.dataflow.base import MiniBatch


def _is_rows(x) -> bool:
    return getattr(x, "ndim", None) == 1 and jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.integer
    )


class DeviceFeatureCache:
    """Device copy of a graph's dense feature table, +1 zero padding row."""

    def __init__(
        self,
        graph,
        feature_names,
        dtype=jnp.float32,
        sharding=None,
        stage_chunk_rows: int | None = None,
        quant: str | None = None,
    ):
        """stage_chunk_rows: stage the table onto the device in row chunks
        instead of one transfer — big tables (hundreds of MB) shipped as a
        single device_put can trip transport limits on proxied/tunneled
        devices; chunking bounds each transfer.

        quant: HBM page dtype — "f32" (exact, the default), "bf16" (half
        the HBM, one rounding per value), or "int8" (quarter the HBM,
        per-row affine scale/zero-point) — defaults to the
        EULER_TPU_PAGE_DTYPE env knob. Dequantize happens inside
        `gather`, where XLA fuses it with the first layer's matmul; the
        error budget per dtype is pinned in PARITY.md and enforced by
        tests. Explicit non-f32 `dtype` wins over `quant` (the caller
        already chose a representation)."""
        from euler_tpu.distributed.codec import page_dtype, quantize

        self.feature_names = list(feature_names)
        host = graph.dense_feature_table(self.feature_names)
        self.dim = host.shape[1]
        table = np.concatenate(
            [np.zeros((1, self.dim), np.float32), host], axis=0
        )
        self.quant = (
            (quant if quant is not None else page_dtype())
            if np.dtype(dtype) == np.float32
            else "f32"
        )
        if self.quant == "int8":
            q, scale, zero = quantize("int8", table)
            # padding row 0 dequantizes to exact zeros: q=0, zero=0
            zero[0] = 0.0
            self._scale = jax.device_put(scale)
            self._zero = jax.device_put(zero)
            table = q
        elif self.quant == "bf16":
            table = table.astype(jnp.bfloat16)
        else:
            table = table.astype(np.dtype(dtype))
        if stage_chunk_rows and len(table) > stage_chunk_rows:
            put = (
                (lambda a: jax.device_put(a, sharding))
                if sharding is not None
                else jax.device_put
            )
            parts = [
                put(table[lo : lo + stage_chunk_rows])
                for lo in range(0, len(table), stage_chunk_rows)
            ]
            self.table = jnp.concatenate(parts, axis=0)
            if sharding is not None:
                self.table = jax.device_put(self.table, sharding)
        else:
            self.table = (
                jax.device_put(table, sharding)
                if sharding is not None
                else jax.device_put(table)
            )

    def gather(self, rows) -> jnp.ndarray:
        """int32 rows (0 = padding) → dense [n, F]; jit-safe. Quantized
        tables dequantize here — next to the consuming matmul, so XLA
        fuses it and the host/HBM copies stay compact."""
        if self.quant == "int8":
            q = self.table[rows].astype(jnp.float32)
            return q * self._scale[rows][..., None] + (
                self._zero[rows][..., None]
            )
        if self.quant == "bf16":
            return self.table[rows].astype(jnp.float32)
        return self.table[rows]

    def _patch(self, rows, vals) -> None:
        """Write f32 values into table rows (row+1 space already applied
        by the caller), re-quantizing to the table's representation."""
        from euler_tpu.distributed.codec import quantize

        if self.quant == "int8":
            q, scale, zero = quantize(
                "int8", np.asarray(vals, np.float32)
            )
            self.table = self.table.at[rows].set(jnp.asarray(q))
            self._scale = self._scale.at[rows].set(jnp.asarray(scale))
            self._zero = self._zero.at[rows].set(jnp.asarray(zero))
            return
        self.table = self.table.at[rows].set(
            jnp.asarray(vals, dtype=self.table.dtype)
        )

    def refresh_rows(self, graph, rows) -> int:
        """Residual re-staging: refetch ONLY the given global rows and
        patch them into the device table (row+1 space, row 0 stays the
        zero/padding row). The cheap path after a `graph_epoch` bump —
        mutated hot rows re-stage in one small transfer instead of
        re-shipping the whole table. Against a remote graph the fetch
        rides `get_dense_by_rows`, so the client read cache's residual
        logic applies to it too. Returns how many rows were re-staged."""
        rows = np.unique(np.asarray(rows, dtype=np.int64).reshape(-1))
        rows = rows[(rows >= 0) & (rows + 1 < self.table.shape[0])]
        if not len(rows):
            return 0
        vals = np.asarray(
            graph.get_dense_by_rows(rows, self.feature_names), np.float32
        )
        self._patch(rows + 1, vals)
        return int(len(rows))

    def hydrate(self, batch):
        """MiniBatch with rows-mode feature slots → dense feature slots.

        Non-MiniBatch args and already-dense batches pass through, so the
        Estimator can apply this uniformly to every model argument.
        """
        if not isinstance(batch, MiniBatch) or not batch.feats:
            return batch
        if not _is_rows(batch.feats[0]):
            return batch
        return batch.replace(
            feats=tuple(self.gather(r) for r in batch.feats)
        )

    def hydrate_args(self, args: tuple) -> tuple:
        return tuple(self.hydrate(a) for a in args)


class ResidualFetchRing:
    """Double-buffered background re-stager for device-resident tables —
    the residual lane of the paged device-sampling flow.

    The device lane stages everything once at construction; afterwards
    the only host↔wire traffic is RESIDUAL: rows invalidated by a
    `graph_epoch` bump, or rows a caller wants re-warmed. Those fetches
    must never stall the device, so they run on a background worker into
    a bounded ring of host buffers (fetch job N+1 is on the wire while
    the trainer consumes job N) and `commit()` patches finished buffers
    into the device table between dispatches — the swap point. Against a
    remote graph the fetch path is `get_dense_by_rows`, a deterministic
    verb served by the PR-5 client ReadCache: staging warmed the cache,
    so residual fetches are mostly client-side hits and
    `stats()["residual_fetch_hit_rate"]` reports exactly that (the bench
    remote lane's telemetry key).

    Epoch handshake: `poll_epoch()` re-reads each remote shard's
    graph_epoch via `refresh_epoch()` (which already flushes that
    shard's ReadCache on a bump) and schedules a residual refresh of the
    tracked rows, so the device table converges on the new epoch without
    a full re-stage — `DeviceFeatureCache.refresh_rows` is the one-shot
    synchronous form of the same move.
    """

    def __init__(self, cache: DeviceFeatureCache, graph, depth: int = 2):
        import queue
        import threading

        self.cache = cache
        self.graph = graph
        self._jobs: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._ready: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._inflight = 0
        self._epochs: dict[int, int] = {}  # bounded: one entry per shard
        # telemetry (GIL-racy increments fine — repo counter stance)
        self.fetched_rows = 0
        self.commits = 0
        base = self._cache_stats()
        self._hit_base = (
            {"hits": base.get("hits", 0), "misses": base.get("misses", 0)}
            if base
            else {"hits": 0, "misses": 0}
        )
        self._worker = threading.Thread(
            target=self._work, daemon=True, name="residual-fetch-ring"
        )
        self._worker.start()

    def _cache_stats(self) -> dict | None:
        from euler_tpu.distributed.cache import graph_cache_stats

        return graph_cache_stats(self.graph)

    # -- producer side ---------------------------------------------------

    def prefetch(self, rows) -> bool:
        """Schedule a residual fetch of the given global rows (row space
        of lookup_rows, NOT row+1). Non-blocking: False when the ring is
        full — the caller retries at the next swap point instead of
        stalling the step."""
        import queue

        rows = np.unique(np.asarray(rows, dtype=np.int64).reshape(-1))
        rows = rows[(rows >= 0) & (rows + 1 < self.cache.table.shape[0])]
        if not len(rows):
            return False
        with self._lock:
            try:
                self._jobs.put_nowait(rows)
            except queue.Full:
                return False
            self._inflight += 1
        return True

    def poll_epoch(self, hot_rows=None) -> bool:
        """Re-observe each shard's graph_epoch (refresh_epoch flushes the
        shard's ReadCache on a bump); on any bump, schedule a residual
        refresh of `hot_rows` (default: the whole table, best-effort —
        repeated polls converge when the ring was full). Returns True
        when a bump was observed."""
        bumped = False
        for sh in getattr(self.graph, "shards", []) or []:
            fn = getattr(sh, "refresh_epoch", None)
            ep = int(fn()) if fn is not None else int(
                getattr(sh, "graph_epoch", 0)
            )
            part = int(getattr(sh, "part", 0))
            with self._lock:
                last = self._epochs.get(part)
                self._epochs[part] = ep
            if last is not None and ep != last:
                bumped = True
        if bumped:
            rows = (
                np.arange(self.cache.table.shape[0] - 1, dtype=np.int64)
                if hot_rows is None
                else np.asarray(hot_rows, dtype=np.int64)
            )
            for lo in range(0, len(rows), 65536):
                if not self.prefetch(rows[lo : lo + 65536]):
                    break  # ring full: the next poll re-schedules
        return bumped

    def on_publish(self, result) -> bool:
        """Eager half of the epoch handshake when the WRITER lives in
        this process: feed `GraphWriter.publish()`'s dict straight in.
        The publish's mutated global rows are scheduled for residual
        refresh (whole table when the publish could not name them), and
        the per-shard epoch book syncs to the published epochs so the
        next `poll_epoch()` doesn't schedule the same refresh twice.
        Remote-only readers keep using `poll_epoch()` — this is the
        zero-latency path for the process that did the publishing.
        Returns True when a refresh was scheduled."""
        rows = result.get("rows") if isinstance(result, dict) else result
        if isinstance(result, dict):
            for part, ep in (result.get("epochs") or {}).items():
                with self._lock:
                    self._epochs[int(part)] = int(ep)
        rows = np.asarray(
            np.arange(self.cache.table.shape[0] - 1) if rows is None
            else rows,
            dtype=np.int64,
        )
        scheduled = False
        for lo in range(0, len(rows), 65536):
            if not self.prefetch(rows[lo : lo + 65536]):
                break  # ring full: poll_epoch/commit cadence catches up
            scheduled = True
        return scheduled

    # -- worker / consumer side ------------------------------------------

    def _work(self):
        while True:
            rows = self._jobs.get()
            if rows is None:
                return
            try:
                vals = np.asarray(
                    self.graph.get_dense_by_rows(
                        rows, self.cache.feature_names
                    ),
                    np.float32,
                )
                self._ready.put((rows, vals))
            except Exception as e:  # surfaced to the caller at commit()
                self._ready.put((rows, e))

    def commit(self) -> int:
        """Patch every FINISHED buffer into the device table (call
        between dispatches). Returns rows patched; re-raises the first
        fetch error, if any."""
        import queue

        n = 0
        err = None
        while True:
            try:
                rows, vals = self._ready.get_nowait()
            except queue.Empty:
                break
            with self._lock:
                self._inflight -= 1
            if isinstance(vals, Exception):
                err = err or vals
                continue
            self.cache._patch(rows + 1, vals)
            n += len(rows)
        if n:
            self.commits += 1
            self.fetched_rows += n
        if err is not None:
            raise err
        return n

    def flush(self, timeout_s: float = 30.0) -> int:
        """Wait for every in-flight fetch and commit it (test/shutdown
        convenience — the training loop uses commit() alone)."""
        import time

        deadline = time.monotonic() + timeout_s
        n = self.commit()
        while True:
            with self._lock:
                idle = self._inflight == 0
            if idle or time.monotonic() > deadline:
                break
            time.sleep(0.005)
            n += self.commit()
        return n + self.commit()

    def stats(self) -> dict:
        st = self._cache_stats() or {}
        hits = int(st.get("hits", 0)) - self._hit_base["hits"]
        misses = int(st.get("misses", 0)) - self._hit_base["misses"]
        lookups = hits + misses
        with self._lock:
            inflight = self._inflight
        return {
            "fetched_rows": self.fetched_rows,
            "commits": self.commits,
            "inflight": inflight,
            "residual_fetch_hit_rate": (
                round(hits / lookups, 4) if lookups else 0.0
            ),
        }

    def close(self):
        self._jobs.put(None)
        self._worker.join(timeout=5.0)
