"""HBM-resident node feature cache.

The reference fetches dense features from the graph engine per batch and
ships them through the TF op boundary (feature_ops.py, get_dense_feature
kernels). On TPU the equivalent boundary — host→device transfer — is the
throughput ceiling: a 2-hop fanout batch carries ~B·k1·k2·F floats. The
TPU-native answer is to load the dense feature table into device HBM once
and ship only int32 row indices per batch; the gather runs on device inside
the jitted step, where XLA fuses it with the first layer's matmul.

Pair with DataFlow(feature_mode="rows"): hop feature slots then hold int32
rows into this cache's table (row 0 = zero/padding row), and
`hydrate(batch)` — called inside jit by the Estimator — turns them back
into dense per-hop matrices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from euler_tpu.dataflow.base import MiniBatch


def _is_rows(x) -> bool:
    return getattr(x, "ndim", None) == 1 and jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.integer
    )


class DeviceFeatureCache:
    """Device copy of a graph's dense feature table, +1 zero padding row."""

    def __init__(
        self,
        graph,
        feature_names,
        dtype=jnp.float32,
        sharding=None,
        stage_chunk_rows: int | None = None,
    ):
        """stage_chunk_rows: stage the table onto the device in row chunks
        instead of one transfer — big tables (hundreds of MB) shipped as a
        single device_put can trip transport limits on proxied/tunneled
        devices; chunking bounds each transfer."""
        self.feature_names = list(feature_names)
        host = graph.dense_feature_table(self.feature_names)
        self.dim = host.shape[1]
        table = np.concatenate(
            [np.zeros((1, self.dim), np.float32), host], axis=0
        )
        table = table.astype(np.dtype(dtype))
        if stage_chunk_rows and len(table) > stage_chunk_rows:
            put = (
                (lambda a: jax.device_put(a, sharding))
                if sharding is not None
                else jax.device_put
            )
            parts = [
                put(table[lo : lo + stage_chunk_rows])
                for lo in range(0, len(table), stage_chunk_rows)
            ]
            self.table = jnp.concatenate(parts, axis=0)
            if sharding is not None:
                self.table = jax.device_put(self.table, sharding)
        else:
            self.table = (
                jax.device_put(table, sharding)
                if sharding is not None
                else jax.device_put(table)
            )

    def gather(self, rows) -> jnp.ndarray:
        """int32 rows (0 = padding) → dense [n, F]; jit-safe."""
        return self.table[rows]

    def refresh_rows(self, graph, rows) -> int:
        """Residual re-staging: refetch ONLY the given global rows and
        patch them into the device table (row+1 space, row 0 stays the
        zero/padding row). The cheap path after a `graph_epoch` bump —
        mutated hot rows re-stage in one small transfer instead of
        re-shipping the whole table. Against a remote graph the fetch
        rides `get_dense_by_rows`, so the client read cache's residual
        logic applies to it too. Returns how many rows were re-staged."""
        rows = np.unique(np.asarray(rows, dtype=np.int64).reshape(-1))
        rows = rows[(rows >= 0) & (rows + 1 < self.table.shape[0])]
        if not len(rows):
            return 0
        vals = np.asarray(
            graph.get_dense_by_rows(rows, self.feature_names), np.float32
        )
        self.table = self.table.at[rows + 1].set(
            jnp.asarray(vals, dtype=self.table.dtype)
        )
        return int(len(rows))

    def hydrate(self, batch):
        """MiniBatch with rows-mode feature slots → dense feature slots.

        Non-MiniBatch args and already-dense batches pass through, so the
        Estimator can apply this uniformly to every model argument.
        """
        if not isinstance(batch, MiniBatch) or not batch.feats:
            return batch
        if not _is_rows(batch.feats[0]):
            return batch
        return batch.replace(
            feats=tuple(self.gather(r) for r in batch.feats)
        )

    def hydrate_args(self, args: tuple) -> tuple:
        return tuple(self.hydrate(a) for a in args)
