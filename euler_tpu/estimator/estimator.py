"""Train/evaluate/infer driver — the reference's `BaseEstimator`
(euler_estimator/python/base_estimator.py:28-188) rebuilt JAX-style.

The model contract matches the reference (mp_utils/base.py:24-95): a flax
module whose __call__ returns (embedding, loss, metric_name, metric). Batches
come from host-side generator functions (graph sampling + dataflow queries),
get device_put, and run through one jitted update step. Checkpointing is
Orbax; inference writes embedding_{worker}.npy / ids_{worker}.npy like
base_estimator.py:157-179.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
import weakref
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass
class EstimatorConfig:
    model_dir: str = "/tmp/euler_tpu_model"
    batch_size: int = 32
    total_steps: int = 100
    learning_rate: float = 0.01
    optimizer: str = "adam"  # adam | adagrad | sgd | momentum
    momentum: float = 0.9
    log_steps: int = 20
    checkpoint_steps: int = 0  # 0 = only at end
    # retained atomic checkpoints (euler_tpu/training/checkpoint.py):
    # save() commits step-numbered ckpt_<step>/ dirs and keeps this many
    # complete ones — a crash mid-save can never lose the previous good
    # state. restore() picks the newest COMPLETE one (legacy single-path
    # Orbax "ckpt" dirs still restore).
    keep_checkpoints: int = 3
    seed: int = 0
    # profiling (BaseEstimator(profiling=True) parity, base_estimator.py:
    # 130-133): when set, a jax.profiler trace of `profile_steps` steps is
    # written there once, starting at `profile_start_step`
    profile_dir: str = ""
    profile_start_step: int = 10
    profile_steps: int = 5
    # steps per XLA dispatch: >1 runs a lax.scan of K optimizer steps over
    # batches stacked on a leading K axis (batch_fn must return them that
    # way, e.g. via `stack_batches`). Amortizes host→device dispatch latency
    # — the TPU analog of the reference keeping its query pipeline async
    # (query_proxy.cc:205-256) so the trainer never stalls per step.
    steps_per_call: int = 1


# The ONE table both the optimizer factory and its cache key derive from:
# per optimizer name, the EstimatorConfig fields the built transformation
# reads. make_optimizer consumes fields only through this table, so a new
# knob that is not declared here raises at construction instead of
# silently sharing one cached update program between differing configs.
_OPTIMIZER_CFG_FIELDS: dict[str, tuple[str, ...]] = {
    "adam": ("learning_rate",),
    "adagrad": ("learning_rate",),
    "sgd": ("learning_rate",),
    "momentum": ("learning_rate", "momentum"),
}

_OPTIMIZER_FACTORIES = {
    "adam": lambda a: optax.adam(a["learning_rate"]),
    "adagrad": lambda a: optax.adagrad(a["learning_rate"]),
    "sgd": lambda a: optax.sgd(a["learning_rate"]),
    "momentum": lambda a: optax.sgd(
        a["learning_rate"], momentum=a["momentum"]
    ),
}


def make_optimizer(cfg: EstimatorConfig) -> optax.GradientTransformation:
    """Optimizer factory (tf_euler/python/utils/optimizers.py parity).
    Reads cfg ONLY through _OPTIMIZER_CFG_FIELDS, which also drives
    _optimizer_key — the factory and the jit-cache key cannot drift."""
    if cfg.optimizer not in _OPTIMIZER_CFG_FIELDS:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    args = {
        f: getattr(cfg, f) for f in _OPTIMIZER_CFG_FIELDS[cfg.optimizer]
    }
    return _OPTIMIZER_FACTORIES[cfg.optimizer](args)


def _optimizer_key(cfg: EstimatorConfig) -> tuple:
    """Shared-jit cache key: derived mechanically from the cfg fields
    make_optimizer consumes for THIS optimizer, so a field the update
    program never reads (e.g. momentum under adam) cannot force a
    spurious retrace, and a consumed field can never be missed."""
    return (cfg.optimizer,) + tuple(
        getattr(cfg, f) for f in _OPTIMIZER_CFG_FIELDS[cfg.optimizer]
    )



# Jitted programs are shared ACROSS Estimator instances: tracing +
# lowering an identical train step costs seconds per instance on a host
# core even when the persistent compile cache spares the XLA compile
# (re-instantiation patterns: determinism reruns, warm-started TransX
# chains, hyperparameter sweeps, serving runtimes). The cache dict is
# keyed BY the user's flow (else feature-cache) object in a module-level
# WeakKeyDictionary — not injected as an attribute onto the user's object
# (ADVICE r5: attribute injection broke copy.deepcopy/pickle of flows
# after training) and not a strong global — so the cached closures never
# outlive the objects whose device buffers they pin: drop the flow/cache
# and the weak entry (and every program traced against it) is freed with
# it. Entries are keyed by everything else the traced program reads: the
# flax model (structural digest), the cfg fields make_optimizer consumes,
# rng collections, the mesh, and the identity of the non-root partner
# object (its id cannot be recycled while the entry exists, because the
# closure holds it). Estimators with neither a device flow nor a feature
# cache have no root to pin the lifetime to and simply keep the
# pre-existing per-instance behavior. Get-or-build runs under
# _JIT_CACHE_LOCK so concurrent serving threads can't race a build.
# EULER_TPU_STEP_CACHE=0 disables all sharing.


def _structural_key(v):
    """Collision-safe, hashable digest of a model's configuration.

    repr(model) alone is NOT safe as a cache key: numpy summarizes large
    arrays ("[0. 0. ... 0.]"), so two models differing only in a big
    constant field repr identically and would silently share one traced
    program — a wrong-result bug, not a perf bug. This walks the
    dataclass fields structurally instead: scalars/strings by value,
    containers recursively, arrays by dtype/shape/content digest, nested
    modules by their own fields. A field of a type this function does not
    understand degrades to identity (`id`) — that model never SHARES a
    cached program (costing a retrace), which is the correct default for
    unknown state.
    """
    import hashlib

    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (list, tuple)):
        return ("seq", tuple(_structural_key(x) for x in v))
    if isinstance(v, dict):
        return (
            "map",
            tuple(
                (str(k), _structural_key(v[k]))
                for k in sorted(v, key=str)
            ),
        )
    if isinstance(v, type):
        return ("type", v.__module__, v.__qualname__)
    if isinstance(v, np.dtype):
        return ("dtype", str(v))
    if hasattr(v, "shape") and hasattr(v, "dtype"):  # numpy / jax array
        arr = np.asarray(v)
        return (
            "array", str(arr.dtype), tuple(arr.shape),
            hashlib.sha1(np.ascontiguousarray(arr).tobytes()).hexdigest(),
        )
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        # nested flax submodule / config dataclass; parent would recurse
        # back up the module tree and name is identity-free metadata
        return (
            "dc", type(v).__module__, type(v).__qualname__,
            tuple(
                (f.name, _structural_key(getattr(v, f.name)))
                for f in dataclasses.fields(v)
                if f.name not in ("parent", "name")
            ),
        )
    if callable(v) and hasattr(v, "__qualname__"):
        # module-level functions (activations etc.) key by location;
        # closures/lambdas share a qualname but can differ in behavior,
        # so they fall through to identity below
        if "<locals>" not in v.__qualname__ and "<lambda>" not in (
            v.__qualname__
        ):
            return ("fn", getattr(v, "__module__", ""), v.__qualname__)
    return ("id", id(v))


# per-root entry bound: each entry's closure can pin a partner object's
# device buffers (e.g. a non-root DeviceFeatureCache's feature table), so
# a sweep that misses every lookup (varying lr / fresh caches against one
# shared flow) must not accumulate pins without bound — FIFO-evicting at
# a small cap frees the evicted closure and everything only it pinned
_JIT_CACHE_MAX = 8


_JIT_CACHES: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# one process-wide reentrant lock over every get-or-build: build work under
# it is cheap (jax.jit only wraps; tracing happens at first call), and a
# single lock cannot deadlock against itself on the nested
# _ensure_steps → _jit_cache path
_JIT_CACHE_LOCK = threading.RLock()


def _jit_cache(root) -> dict | None:
    """The per-object jit-program cache rooted on `root`, or None when
    sharing is off / there is no root."""
    if root is None or os.environ.get("EULER_TPU_STEP_CACHE", "1") == "0":
        return None
    with _JIT_CACHE_LOCK:
        cache = _JIT_CACHES.get(root)
        if cache is None:
            try:
                _JIT_CACHES[root] = cache = {}
            except TypeError:  # not weak-referenceable: no sharing
                return None
    return cache


def _jit_cache_put(cache: dict, key, value):
    # "probe" is exempt from eviction: it is the first insertion and the
    # one entry every Estimator on the flow re-uses, so FIFO would recycle
    # exactly the wrong entry in an all-miss sweep
    evictable = [k for k in cache if k != "probe"]
    while len(evictable) >= _JIT_CACHE_MAX:
        cache.pop(evictable.pop(0))
    cache[key] = value


def _flow_probe(flow):
    """Jitted flow.sample for the init-shape probe, memoized on the flow
    (a fresh jax.jit wrapper would re-trace for every Estimator sharing
    the flow)."""
    cache = _jit_cache(flow)
    if cache is None:
        return jax.jit(flow.sample)
    with _JIT_CACHE_LOCK:
        if "probe" not in cache:
            _jit_cache_put(cache, "probe", jax.jit(flow.sample))
        return cache["probe"]


def _hydrate_batch(feature_cache, batch: tuple) -> tuple:
    from euler_tpu.dataflow.base import MiniBatch, hydrate_blocks

    batch = tuple(
        hydrate_blocks(b) if isinstance(b, MiniBatch) else b for b in batch
    )
    return (
        feature_cache.hydrate_args(batch)
        if feature_cache is not None
        else batch
    )


def _apply_update(model, tx, feature_cache, params, opt_state, step_rngs, batch):
    """One traced optimizer step: hydrate → loss/grad → update."""
    batch = _hydrate_batch(feature_cache, batch)

    def loss_fn(p):
        _, loss, _, metric = model.apply(p, *batch, rngs=step_rngs)
        return loss, metric

    (loss, metric), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, metric


def _step_args(device_flow, xs):
    """Per-step scan/step input → model args. Host flows ship the batch
    itself; device flows ship a PRNG key and sample on device. A flow
    returning a tuple supplies multiple model args (e.g. the unsupervised
    (src, pos, negs) triple)."""
    if device_flow is not None:
        out = device_flow.sample(xs[0])
        return out if isinstance(out, tuple) else (out,)
    return xs


def _build_train_steps(model, tx, device_flow, feature_cache):
    """The two jitted update programs, closing over ONLY the objects the
    trace reads — shareable across Estimator instances via _jit_cache
    without pinning any instance's params."""

    # donate params+opt_state: without it the update keeps both old and
    # new buffers alive across the step — 2x the HBM for model state
    # (the big cost for sharded embedding tables)
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, rngs, *batch):
        return _apply_update(
            model, tx, feature_cache,
            params, opt_state, rngs, _step_args(device_flow, batch),
        )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def multi_step(params, opt_state, rngs, *stacked_batch):
        def body(carry, xs):
            params, opt_state = carry
            step_rngs, batch = xs
            params, opt_state, loss, metric = _apply_update(
                model, tx, feature_cache,
                params, opt_state, step_rngs, _step_args(device_flow, batch),
            )
            return (params, opt_state), (loss, metric)

        (params, opt_state), (losses, metrics) = jax.lax.scan(
            body, (params, opt_state), (rngs, stacked_batch)
        )
        return params, opt_state, losses, metrics[-1]

    return train_step, multi_step


class Estimator:
    """Drives a (emb, loss, metric_name, metric) flax model.

    batch_fn() must return a *tuple* of pytrees passed as model args —
    (MiniBatch,) for supervised heads, (src, pos, negs) for unsupervised.
    """

    def __init__(
        self,
        model,
        batch_fn: Callable[[], tuple],
        cfg: EstimatorConfig | None = None,
        mesh=None,
        feature_cache=None,
        init_params=None,
    ):
        """init_params: warm-start parameter pytree (already unboxed) —
        overrides model.init at first train/eval. Staged recipes use this:
        e.g. TransR/TransD initialized from a trained TransE's tables
        (the published TransR training protocol)."""
        self.model = model
        self.batch_fn = batch_fn
        # a DeviceSageFlow (is_device_flow) generates batches ON the
        # device inside the jitted step from per-step PRNG keys — the
        # drivers then ship keys instead of batches (zero wire bytes)
        self._device_flow = (
            batch_fn if getattr(batch_fn, "is_device_flow", False) else None
        )
        self.cfg = cfg or EstimatorConfig()
        self.mesh = mesh  # jax.sharding.Mesh → data-parallel + sharded tables
        # DeviceFeatureCache: batches arrive as int32 feature rows and are
        # hydrated to dense features on device, inside the jitted step
        self.feature_cache = feature_cache
        self.params = None
        self._init_params = init_params
        self.opt_state = None
        self.step = 0
        # losses fetched by the most recent train() — populated even
        # when the loop raises (try/finally drain), so a crash surfaces
        # the trajectory observed so far
        self.last_losses: list = []
        self.tx = make_optimizer(self.cfg)
        # models may declare extra rng collections (e.g. VGAE's "reparam")
        self._rng_names = tuple(getattr(model, "rng_collections", ()))
        self._base_key = jax.random.PRNGKey((cfg or EstimatorConfig()).seed + 1)
        # device-flow sampling keys: folded per GLOBAL step, so the batch
        # sequence is deterministic and independent of steps_per_call
        self._flow_key = jax.random.PRNGKey(self.cfg.seed + 2)
        if self._device_flow is not None:
            fm = getattr(self._device_flow, "mesh", None)
            if (fm is None) != (mesh is None) or (
                mesh is not None and fm != mesh
            ):
                raise ValueError(
                    "device-flow training needs the Estimator and the flow "
                    "to share one mesh (DeviceSageFlow(..., mesh=mesh)) so "
                    "sampled batches are data-axis sharded; got flow mesh "
                    f"{fm} vs estimator mesh {mesh}"
                )
        self._jit_train = None
        self._jit_train_scan = None
        self._jit_eval = None
        self._jit_embed = None

    # -- state -----------------------------------------------------------

    def _put(self, batch, stacked: bool = False):
        if self.mesh is None:
            return batch
        from euler_tpu.parallel import shard_batch

        # stacked [K_steps, batch, ...] items shard axis 1 (the real batch
        # axis); the scan axis stays unsharded
        return shard_batch(batch, self.mesh, batch_axis=1 if stacked else 0)

    def _hydrate(self, batch: tuple) -> tuple:
        return _hydrate_batch(self.feature_cache, batch)

    def _ensure_init(self):
        if self.params is not None:
            if self.opt_state is None:
                self.opt_state = self.tx.init(self.params)
            return
        import flax.linen as nn

        if self._init_params is not None and self.mesh is None:
            # COPY the warm-start arrays: the donated train step would
            # otherwise invalidate the caller's buffers on TPU (e.g. a
            # trained TransE whose tables seed TransR via
            # transx_warm_start) — buffer donation is a no-op on CPU, so
            # only real-device runs would hit the corruption
            self.params = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), self._init_params
            )
            self.opt_state = self.tx.init(self.params)
            return
        if self._device_flow is not None:
            out = _flow_probe(self._device_flow)(self._flow_keys(0, 1)[0])
            batch = out if isinstance(out, tuple) else (out,)
        else:
            batch = self._put(
                self.batch_fn(), stacked=self.cfg.steps_per_call > 1
            )
            if self.cfg.steps_per_call > 1:  # stacked [K,...] → init slice 0
                batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        batch = self._hydrate(batch)
        key = jax.random.PRNGKey(self.cfg.seed)
        keys = jax.random.split(key, 1 + len(self._rng_names))
        rngs = {"params": keys[0]}
        rngs.update(dict(zip(self._rng_names, keys[1:])))
        params = self.model.init(rngs, *batch)
        if self.mesh is not None:
            from euler_tpu.parallel import unbox_and_shard

            params, _ = unbox_and_shard(self.mesh, params)
            if self._init_params is not None:
                # warm-start under a mesh: the cold init above provides
                # the placement template (row-sharded tables etc.); the
                # warm values are device_put onto the same shardings so
                # model parallelism survives the warm start. copy=True is
                # load-bearing: device_put aliases a src that already has
                # the target sharding, and the donated train step would
                # then delete the CALLER's buffers (the donor model's
                # params) on real devices
                params = jax.tree_util.tree_map(
                    lambda tgt, src: jax.device_put(
                        jnp.array(src, copy=True), tgt.sharding
                    ),
                    params,
                    self._init_params,
                )
        else:
            params = nn.meta.unbox(params)
        self.params = params
        self.opt_state = self.tx.init(self.params)

    def _rngs(self, step: int):
        if not self._rng_names:
            return None
        k = jax.random.fold_in(self._base_key, step)
        return dict(zip(self._rng_names, jax.random.split(k, len(self._rng_names))))



    def _model_key(self) -> tuple:
        m = self.model
        return (type(m).__module__, type(m).__qualname__, _structural_key(m))

    def _ensure_steps(self):
        """Bind the jitted step pair, shared via the root object's jit
        cache when possible (see _jit_cache above)."""
        if self._jit_train is not None:
            return
        # root on the flow when there is one (the closure pins both flow
        # and cache; the flow outliving the cache is the unusual case),
        # else on the feature cache
        root = (
            self._device_flow
            if self._device_flow is not None
            else self.feature_cache
        )
        cache = _jit_cache(root)
        if cache is None:
            # same lock as the shared-cache path below: an Estimator shared
            # by serving threads with sharing disabled must still agree on
            # ONE program pair instead of racing build-and-overwrite
            # (build is cheap under the lock — jax.jit only wraps)
            with _JIT_CACHE_LOCK:
                if self._jit_train is None:
                    self._jit_train, self._jit_train_scan = (
                        _build_train_steps(
                            self.model, self.tx, self._device_flow,
                            self.feature_cache,
                        )
                    )
            return
        key = (
            "steps",
            self._model_key(),
            _optimizer_key(self.cfg),
            self._rng_names,
            id(self.feature_cache)
            if self.feature_cache is not None and root is not self.feature_cache
            else None,
            self.mesh,
        )
        # get-or-build under the lock: two serving/training threads racing
        # here must agree on ONE program pair, not each build-and-overwrite
        with _JIT_CACHE_LOCK:
            if key not in cache:
                _jit_cache_put(
                    cache,
                    key,
                    _build_train_steps(
                        self.model, self.tx, self._device_flow,
                        self.feature_cache,
                    ),
                )
            self._jit_train, self._jit_train_scan = cache[key]

    def _train_step(self):
        self._ensure_steps()
        return self._jit_train

    def _train_step_scan(self):
        """K optimizer steps per dispatch via lax.scan over stacked batches
        (host flows) or per-step sampling keys (device flows)."""
        self._ensure_steps()
        return self._jit_train_scan

    def _rngs_stacked(self, step: int, k: int):
        if not self._rng_names:
            return None
        return jax.vmap(lambda s: self._rngs(s))(jnp.arange(step, step + k))

    def _flow_keys(self, step: int, k: int):
        """[k]-stacked device-flow sampling keys for global steps
        step..step+k (fold_in per step: the batch stream is reproducible
        and invariant to how steps are grouped into dispatches)."""
        return jax.vmap(lambda s: jax.random.fold_in(self._flow_key, s))(
            jnp.arange(step, step + k)
        )

    def _next_batch(self, k: int):
        """One dispatch's batch args: K-stacked host batch or K sampling
        keys (device flow)."""
        if self._device_flow is not None:
            if k > 1:
                return (self._flow_keys(self.step, k),)
            return (jax.random.fold_in(self._flow_key, self.step),)
        return self._put(self.batch_fn(), stacked=k > 1)

    # -- drivers (train/evaluate/infer/train_and_evaluate) ---------------

    def train(
        self, total_steps: int | None = None, log: bool = True, save: bool = True
    ):
        self._ensure_init()
        steps = total_steps if total_steps is not None else self.cfg.total_steps
        k = max(int(self.cfg.steps_per_call), 1)
        if k > 1:
            return self._train_scan(steps, k, log=log, save=save)
        step_fn = self._train_step()
        t0 = time.time()
        history = []  # on-device losses not yet drained to the host
        fetched: list[float] = []
        # drain in chunks: keeping one live device scalar per step for a
        # long run pins an unbounded number of small device buffers
        drain_every = 4096
        profiling = False
        try:
            for _ in range(steps):
                if (
                    self.cfg.profile_dir
                    and not getattr(self, "_profiled", False)
                    and self.step >= self.cfg.profile_start_step
                ):
                    jax.profiler.start_trace(self.cfg.profile_dir)
                    profiling = True
                    profile_stop = self.step + self.cfg.profile_steps
                    self._profiled = True
                batch = self._next_batch(1)
                self.params, self.opt_state, loss, metric = step_fn(
                    self.params, self.opt_state, self._rngs(self.step), *batch
                )
                self.step += 1
                if profiling and self.step >= profile_stop:
                    jax.block_until_ready(loss)
                    jax.profiler.stop_trace()
                    profiling = False
                if log and self.step % self.cfg.log_steps == 0:
                    loss_v = float(loss)
                    dt = time.time() - t0
                    print(
                        f"step {self.step}: loss={loss_v:.4f} "
                        f"metric={float(metric):.4f} ({self.step / dt:.1f} it/s)"
                    )
                # keep losses on device — a float() here would force a
                # blocking device→host round trip every step and
                # serialize the pipeline
                history.append(loss)
                if len(history) >= drain_every:
                    fetched.extend(np.asarray(jnp.stack(history)).tolist())
                    history = []
                if (
                    self.cfg.checkpoint_steps
                    and self.step % self.cfg.checkpoint_steps == 0
                ):
                    self.save()
        finally:
            # a raising loop (dead shard, OOM, poisoned batch) must still
            # surface the losses fetched so far and leave a best-effort
            # checkpoint — previously both were silently dropped
            history, fetched = self._finish_train(
                history, fetched, profiling, save
            )
        return fetched

    def _finish_train(self, history, fetched, profiling, save, concat=False):
        """Shared train-loop epilogue, run from a `finally`: stop a live
        profiler trace, drain the on-device loss history, publish the
        losses fetched so far on `self.last_losses`, and save. When an
        exception is unwinding, the drain and the save are best-effort
        (the original error stays the one surfaced); on the clean path a
        save failure still raises."""
        import sys as _sys

        exc_live = _sys.exc_info()[0] is not None
        if profiling:
            try:
                jax.block_until_ready(self.params)
                jax.profiler.stop_trace()
            except Exception:
                pass
        if history:
            try:
                joined = jnp.concatenate(history) if concat else jnp.stack(
                    history
                )
                fetched.extend(np.asarray(joined).tolist())
                history = []
            except Exception:
                if not exc_live:
                    raise
        self.last_losses = list(fetched)
        if save and self.params is not None:
            if exc_live:
                try:
                    self.save()
                except Exception as e:
                    print(
                        f"# estimator: best-effort checkpoint after a "
                        f"raising train loop failed: {e!r}",
                        file=_sys.stderr,
                    )
            else:
                self.save()
        return history, fetched

    def _train_scan(self, steps: int, k: int, log: bool, save: bool):
        """Driver for steps_per_call>1: each batch_fn() item is a K-stacked
        batch; one jitted dispatch advances K optimizer steps. A non-multiple
        remainder (steps % k) runs through the single-step path on slices of
        one final stacked item, so exactly `steps` updates are applied."""
        step_fn = self._train_step_scan()
        t0 = time.time()
        history = []
        fetched: list[float] = []
        drain_every = max(4096 // k, 1)
        calls, remainder = divmod(steps, k)
        profiling = False
        try:
            for _ in range(calls):
                if (
                    self.cfg.profile_dir
                    and not getattr(self, "_profiled", False)
                    and self.step >= self.cfg.profile_start_step
                ):
                    jax.profiler.start_trace(self.cfg.profile_dir)
                    profiling = True
                    profile_stop = self.step + max(self.cfg.profile_steps, k)
                    self._profiled = True
                batch = self._next_batch(k)
                rngs = self._rngs_stacked(self.step, k)
                self.params, self.opt_state, losses, metric = step_fn(
                    self.params, self.opt_state, rngs, *batch
                )
                self.step += k
                if profiling and self.step >= profile_stop:
                    jax.block_until_ready(losses)
                    jax.profiler.stop_trace()
                    profiling = False
                if log and self.step % max(self.cfg.log_steps, 1) < k:
                    dt = time.time() - t0
                    print(
                        f"step {self.step}: loss={float(losses[-1]):.4f} "
                        f"metric={float(metric):.4f} "
                        f"({self.step / dt:.1f} it/s)"
                    )
                history.append(losses)
                if len(history) >= drain_every:
                    fetched.extend(
                        np.asarray(jnp.concatenate(history)).tolist()
                    )
                    history = []
                if (
                    self.cfg.checkpoint_steps
                    and self.step % self.cfg.checkpoint_steps < k
                ):
                    self.save()
            if profiling:
                jax.block_until_ready(self.params)
                jax.profiler.stop_trace()
                profiling = False
            if remainder:
                single = self._train_step()
                item = (
                    (self._flow_keys(self.step, remainder),)
                    if self._device_flow is not None
                    else self._put(self.batch_fn(), stacked=True)
                )
                for i in range(remainder):
                    batch = jax.tree_util.tree_map(lambda x: x[i], item)
                    self.params, self.opt_state, loss, _ = single(
                        self.params, self.opt_state, self._rngs(self.step),
                        *batch,
                    )
                    self.step += 1
                    history.append(loss[None])
        finally:
            # same contract as train(): a raising loop still drains the
            # fetched losses and leaves a best-effort checkpoint
            history, fetched = self._finish_train(
                history, fetched, profiling, save, concat=True
            )
        return fetched[:steps]

    def _shared_apply_jit(self, kind: str, build):
        """Get-or-build an eval/embed program, rooted on the feature
        cache (the only instance object those programs read besides the
        model)."""
        cache = _jit_cache(self.feature_cache)
        if cache is None:
            return build()
        key = (kind, self._model_key(), self._rng_names)
        with _JIT_CACHE_LOCK:
            if key not in cache:
                _jit_cache_put(cache, key, build())
            return cache[key]

    def evaluate(self, batches: Iterable[tuple]) -> dict:
        self._ensure_init()
        if self._jit_eval is None:
            model, fc = self.model, self.feature_cache
            self._jit_eval = self._shared_apply_jit(
                "eval",
                lambda: jax.jit(
                    lambda p, rngs, *b: model.apply(
                        p, *_hydrate_batch(fc, b), rngs=rngs
                    )[1:4:2]
                ),
            )  # (loss, metric)
        name = getattr(self, "_metric_name", None)
        losses, metrics = [], []
        for batch in batches:
            batch = self._put(batch)
            loss, metric = self._jit_eval(self.params, self._rngs(0), *batch)
            if name is None:
                # the metric NAME is a static python string the jitted
                # program can't return; one eager forward fetches it, once
                # per Estimator (not per evaluate call)
                name = self._metric_name = self.model.apply(
                    self.params, *self._hydrate(batch), rngs=self._rngs(0)
                )[2]
            losses.append(float(loss))
            metrics.append(float(metric))
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            (name or "metric"): float(np.mean(metrics)) if metrics else float("nan"),
        }

    def embed_program(self):
        """The jitted `(params, batch) -> embeddings` program `infer` runs —
        shared across instances via the feature-cache-rooted jit cache, and
        the program the serving runtime executes so served predictions are
        bit-identical to offline `infer` on the same checkpoint."""
        if self._jit_embed is None:
            model, fc = self.model, self.feature_cache
            self._jit_embed = self._shared_apply_jit(
                "embed",
                lambda: jax.jit(
                    lambda p, b: model.apply(
                        p, *_hydrate_batch(fc, (b,)), method=model.embed
                    )
                ),
            )
        return self._jit_embed

    def infer(
        self, batches: Iterable[tuple], ids: Iterable[np.ndarray], worker: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        """Embeds batches; writes embedding_{worker}.npy + ids_{worker}.npy."""
        self._ensure_init()
        self.embed_program()
        embs, all_ids = [], []
        for batch, chunk_ids in zip(batches, ids):
            batch = self._put(batch)
            emb = np.asarray(self._jit_embed(self.params, batch[0]))
            embs.append(emb[: len(chunk_ids)])
            all_ids.append(np.asarray(chunk_ids))
        emb = np.concatenate(embs) if embs else np.zeros((0, 0))
        idv = np.concatenate(all_ids) if all_ids else np.zeros((0,), np.uint64)
        os.makedirs(self.cfg.model_dir, exist_ok=True)
        np.save(os.path.join(self.cfg.model_dir, f"embedding_{worker}.npy"), emb)
        np.save(os.path.join(self.cfg.model_dir, f"ids_{worker}.npy"), idv)
        return idv, emb

    def train_and_evaluate(self, eval_batches_fn, eval_every: int):
        """Alternate train/eval (base_estimator train_and_evaluate parity)."""
        results = []
        remaining = self.cfg.total_steps
        while remaining > 0:
            chunk = min(eval_every, remaining)
            self.train(chunk)
            results.append(self.evaluate(eval_batches_fn()))
            remaining -= chunk
        return results

    # -- checkpointing ---------------------------------------------------

    def save(self) -> str:
        """Commit one retained atomic checkpoint (`ckpt_<step>/` under
        model_dir: tmp + fsync + rename + COMMIT marker, keep-N GC).

        The old behavior — overwrite ONE fixed Orbax path with
        force=True — meant a kill -9 mid-save destroyed the only
        checkpoint in existence; now the previous complete checkpoint
        survives any crash point of this write. Returns the committed
        path."""
        from euler_tpu.training.checkpoint import CheckpointStore

        self._ensure_init()
        p_leaves, _ = jax.tree_util.tree_flatten(self.params)
        o_leaves, _ = jax.tree_util.tree_flatten(self.opt_state)
        store = CheckpointStore(
            self.cfg.model_dir, keep=self.cfg.keep_checkpoints
        )
        return store.save_leaves(
            self.step,
            [np.asarray(jax.device_get(x)) for x in p_leaves],
            [np.asarray(jax.device_get(x)) for x in o_leaves],
            {"seed": int(self.cfg.seed)},
        )

    def restore(self) -> bool:
        """Restore the newest COMPLETE retained checkpoint (torn dirs —
        a crash mid-save — are invisible by construction), falling back
        to a legacy single-path Orbax `ckpt` dir for pre-retained
        model_dirs."""
        from euler_tpu.training.checkpoint import CheckpointStore

        store = CheckpointStore(
            self.cfg.model_dir, keep=self.cfg.keep_checkpoints
        )
        step = store.latest_step()
        if step is not None:
            self._ensure_init()
            ckpt = store.load(step)

            def onto(saved, live):
                leaves, tdef = jax.tree_util.tree_flatten(live)
                if len(saved) != len(leaves):
                    raise ValueError(
                        f"checkpoint ckpt_{step:012d} carries {len(saved)} "
                        f"leaves where the live tree has {len(leaves)} — "
                        "model/optimizer config drifted from the saved run"
                    )
                put = [
                    jax.device_put(s, x.sharding)
                    if isinstance(x, jax.Array)
                    else jnp.asarray(s)
                    for s, x in zip(saved, leaves)
                ]
                return jax.tree_util.tree_unflatten(tdef, put)

            self.params = onto(ckpt["params"], self.params)
            self.opt_state = onto(ckpt["opt_state"], self.opt_state)
            self.step = int(ckpt["step"])
            return True
        return self._restore_legacy_orbax()

    def _restore_legacy_orbax(self) -> bool:
        import orbax.checkpoint as ocp

        path = os.path.join(os.path.abspath(self.cfg.model_dir), "ckpt")
        if not os.path.exists(path):
            return False
        self._ensure_init()
        ckpt = ocp.PyTreeCheckpointer()
        # pre-opt_state checkpoints carry only params+step: detect by the
        # checkpoint's own metadata, so genuine restore errors propagate
        # instead of silently resetting optimizer slots. Orbax returns the
        # tree metadata as a plain dict (>=0.7) or wrapped in an object
        # with .item_metadata (older releases).
        meta = ckpt.metadata(path)
        if not hasattr(meta, "keys"):
            meta = meta.item_metadata
        has_opt = "opt_state" in set(meta.keys())

        def _args(tpl):
            # restore each leaf straight onto the live tree's sharding
            # (orbax otherwise re-reads it from the sharding file, with a
            # warning, and the arrays land unsharded on meshes)
            return jax.tree_util.tree_map(
                lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
                if isinstance(x, jax.Array)
                else ocp.RestoreArgs(),
                tpl,
            )

        item = {"params": self.params, "step": 0}
        if has_opt:
            item["opt_state"] = self.opt_state
        restored = ckpt.restore(path, item=item, restore_args=_args(item))
        self.opt_state = (
            restored["opt_state"]
            if has_opt
            else self.tx.init(restored["params"])
        )
        self.params = restored["params"]
        self.step = int(restored["step"])
        return True


def stack_batches(batch_fn: Callable[[], tuple], k: int) -> Callable[[], tuple]:
    """Wrap a batch source to return K batches stacked on a leading axis,
    for `EstimatorConfig.steps_per_call=K` scan training."""

    def fn():
        batches = [batch_fn() for _ in range(k)]
        try:
            return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
        except ValueError:
            # the usual cause: a lean dataflow downgraded mid-window, so
            # some batches carry masks/edge_w arrays and others None.
            # Hydrating the lean ones host-side is exact (they satisfied
            # the lean invariants) and makes the window stackable.
            from euler_tpu.dataflow.base import upgrade_lean_host

            batches = [
                tuple(upgrade_lean_host(x) for x in bt) for bt in batches
            ]
            try:
                return jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *batches
                )
            except ValueError as e:
                raise ValueError(
                    "steps_per_call>1 requires every batch in a window to "
                    "have identical pytree structure; got a mix that lean "
                    "hydration could not reconcile (a batch_fn with "
                    f"varying structure?). Original error: {e}"
                ) from e

    return fn


# ---- batch sources (Node/Edge estimator input_fn parity) ----------------


def _shard_failure_wrap(fn, on_shard_failure: str, max_skips: int):
    """Shard-failure policy for training readers: "raise" (default)
    surfaces the typed error; "skip" drops the failed BATCH and draws the
    next one, so a dead shard degrades epoch throughput (batches routed
    to surviving coordinators keep flowing) instead of killing the run.
    Bounded: more than `max_skips` CONSECUTIVE failures re-raises — a
    fully dead cluster must not spin forever. `wrapped.skipped` counts
    dropped batches (telemetry: proves degradation was visible, not
    silent)."""
    if on_shard_failure not in ("raise", "skip"):
        raise ValueError(f"on_shard_failure: {on_shard_failure!r}")
    if on_shard_failure == "raise":
        return fn

    from euler_tpu.distributed.errors import RpcError

    def wrapped():
        skips = 0
        while True:
            try:
                return fn()
            except RpcError as e:
                wrapped.skipped += 1
                skips += 1
                if skips > max_skips:
                    raise RpcError(
                        f"skip-batch policy gave up after {skips}"
                        f" consecutive failures: {e}"
                    ) from e

    wrapped.skipped = 0
    return wrapped


def pipelined_batches(
    flow,
    batch_size: int,
    depth: int = 4,
    node_type: int = -1,
    on_shard_failure: str = "raise",
    max_skips: int = 16,
) -> Callable[[], tuple]:
    """Remote batch source with `depth` overlapped sage_minibatch RPCs.

    The reference client overlaps requests through gRPC completion queues
    (query_proxy.cc:235-256); here a rolling window of Futures keeps the
    shard servers busy while the head batch is consumed, hiding one-RPC
    latency behind its successors. Falls back to sync flow.minibatch when
    the graph has no async surface (in-process graphs). Thread-safe: may
    be wrapped in a Prefetcher with multiple workers."""
    from collections import deque

    pending: deque = deque()
    lock = threading.Lock()
    sync_mode = [False]  # sticky downgrade: no async surface / old server

    def fn():
        with lock:
            if not sync_mode[0]:
                while len(pending) < max(depth, 1):
                    fut = flow.minibatch_async(batch_size, node_type)
                    if fut is None:  # no async surface → stay sync
                        sync_mode[0] = True
                        break
                    pending.append(fut)
            if sync_mode[0] and not pending:
                # sync minibatch under the lock: flow.rng is a shared
                # numpy Generator, not thread-safe across workers
                return (flow.minibatch(batch_size, node_type),)
            head = pending.popleft()
        try:
            return (head.result(),)
        except RuntimeError as e:
            if "unknown op" not in str(e):
                raise
            # pre-async server: downgrade stays sticky — stop refilling
            # the window with doomed RPCs, drop the in-flight ones
            with lock:
                sync_mode[0] = True
                pending.clear()
                return (flow.minibatch(batch_size, node_type),)

    return _shard_failure_wrap(fn, on_shard_failure, max_skips)


def node_batches(
    graph,
    flow,
    batch_size: int,
    node_type: int = -1,
    rng=None,
    on_shard_failure: str = "raise",
    max_skips: int = 16,
) -> Callable[[], tuple]:
    """Training source: sample root nodes per step
    (node_estimator.py:31-37). on_shard_failure="skip" drops batches that
    die on a failed shard instead of killing the epoch (bounded; see
    _shard_failure_wrap)."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        roots = graph.sample_node(batch_size, node_type, rng=rng)
        return (flow.query(roots),)

    return _shard_failure_wrap(fn, on_shard_failure, max_skips)


def edge_batches(
    graph, flow, batch_size: int, edge_type: int = -1, rng=None
) -> Callable[[], tuple]:
    """Training source over sampled edges: returns src-node batches with the
    dst id as positive context (edge_estimator parity)."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        edges = graph.sample_edge(batch_size, edge_type, rng=rng)
        return (flow.query(edges[:, 0]), flow.query(edges[:, 1]))

    return fn


def unsupervised_batches(
    graph,
    flow,
    batch_size: int,
    node_type: int = -1,
    edge_types=None,
    num_negs: int = 5,
    neg_type: int = -1,
    rng=None,
) -> Callable[[], tuple]:
    """(src, pos, negs) source for UnsuperviseModel (mp_utils/base.py:52-95):
    pos = sampled 1-hop neighbor of src, negs = globally sampled nodes."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        src = graph.sample_node(batch_size, node_type, rng=rng)
        nbr, _, _, mask, _ = graph.sample_neighbor(src, edge_types, 1, rng=rng)
        pos = np.where(mask[:, 0], nbr[:, 0], src)
        negs = graph.sample_node(batch_size * num_negs, neg_type, rng=rng)
        return (flow.query(src), flow.query(pos), flow.query(negs))

    return fn


def _padded_chunks(ids: np.ndarray, batch_size: int) -> Iterator[np.ndarray]:
    """Fixed-size id chunks; the last one pads by repeating its final id."""
    for i in range(0, len(ids), batch_size):
        chunk = ids[i : i + batch_size]
        if len(chunk) < batch_size:  # pad to keep shapes static
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[-1:], batch_size - len(chunk))]
            )
        yield chunk


def read_sample_ids(path: str, column: int = 0) -> np.ndarray:
    """u64 root ids from a comma-separated sample file (one sample/line)."""
    from euler_tpu.utils.file_io import open_file

    with open_file(path, "r") as f:
        rows = [line.strip().split(",") for line in f if line.strip()]
    return np.asarray([np.uint64(r[column]) for r in rows], dtype=np.uint64)


def sample_file_batches(
    flow,
    path: str,
    batch_size: int,
    epochs: int = 1,
    column: int = 0,
) -> Iterator[tuple]:
    """Training source from comma-separated sample files
    (SampleEstimator parity, euler_estimator sample_estimator.py): each
    line holds CSV fields; `column` selects the root node id field. Yields
    padded fixed-size batches for `epochs` passes. The final batch repeats
    its last id to keep shapes static — for exact evaluation/inference over
    a sample file, pass `read_sample_ids(path)` to `id_batches`, whose id
    chunks identify the padding."""
    ids = read_sample_ids(path, column)
    for _ in range(epochs):
        for chunk in _padded_chunks(ids, batch_size):
            yield (flow.query(chunk),)


def id_batches(
    flow, ids: np.ndarray, batch_size: int
) -> tuple[Iterator[tuple], Iterator[np.ndarray]]:
    """Fixed-id evaluation/inference source (chunked, last chunk padded)."""
    ids = np.asarray(ids, dtype=np.uint64)

    def batches():
        for chunk in _padded_chunks(ids, batch_size):
            yield (flow.query(chunk),)

    def id_chunks():
        for i in range(0, len(ids), batch_size):
            yield ids[i : i + batch_size]

    return batches(), id_chunks()
