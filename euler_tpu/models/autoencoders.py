"""Graph auto-encoders and contrastive models: GAE, VGAE, DGI
(examples/gae, examples/dgi parity)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.dataflow.base import MiniBatch
from euler_tpu.nn.base_gnn import GNNNet
from euler_tpu.nn.metrics import auc


class GAE(nn.Module):
    """GCN encoder + inner-product edge decoder.

    Batch: (src_mb, dst_mb, neg_mb) — positive edges (src→dst) vs sampled
    negative pairs (src→neg). variational=True adds the VGAE KL term.
    """

    dims: Sequence[int]
    variational: bool = False
    kl_weight: float = 1e-2
    remat: bool = False  # rematerialize conv layers (GNNNet.remat)

    rng_collections = ("reparam",)  # consumed by Estimator

    def setup(self):
        self.encoder = GNNNet(conv="gcn", dims=self.dims, remat=self.remat)
        if self.variational:
            self.mu_head = nn.Dense(self.dims[-1])
            self.logvar_head = nn.Dense(self.dims[-1])

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        h = self.encoder(batch)
        return self.mu_head(h) if self.variational else h

    def _encode(self, batch, rng):
        h = self.encoder(batch)
        if not self.variational:
            return h, 0.0
        mu = self.mu_head(h)
        logvar = self.logvar_head(h)
        std = jnp.exp(0.5 * logvar)
        z = mu + std * jax.random.normal(rng, mu.shape)
        kl = -0.5 * jnp.mean(
            jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
        )
        return z, kl

    def __call__(self, src: MiniBatch, dst: MiniBatch, neg: MiniBatch):
        rng = self.make_rng("reparam") if self.variational else None
        k1 = k2 = k3 = None
        if self.variational:
            k1, k2, k3 = jax.random.split(rng, 3)
        z_src, kl1 = self._encode(src, k1)
        z_dst, kl2 = self._encode(dst, k2)
        z_neg, kl3 = self._encode(neg, k3)
        pos_logit = jnp.sum(z_src * z_dst, axis=-1)
        neg_logit = jnp.sum(z_src * z_neg, axis=-1)
        logits = jnp.concatenate([pos_logit, neg_logit])
        labels = jnp.concatenate(
            [jnp.ones_like(pos_logit), jnp.zeros_like(neg_logit)]
        )
        loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))
        if self.variational:
            loss = loss + self.kl_weight * (kl1 + kl2 + kl3) / 3.0
        return z_src, loss, "auc", auc(labels, logits)


class DGI(nn.Module):
    """Deep Graph Infomax: real vs feature-shuffled batch against a global
    readout through a bilinear discriminator (examples/dgi)."""

    dims: Sequence[int]
    remat: bool = False  # rematerialize conv layers (GNNNet.remat)

    def setup(self):
        self.encoder = GNNNet(conv="gcn", dims=self.dims, remat=self.remat)
        d = self.dims[-1]
        self.bilinear = self.param(
            "bilinear", nn.initializers.lecun_normal(), (d, d)
        )

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        return self.encoder(batch)

    def __call__(self, batch: MiniBatch, corrupt: MiniBatch):
        h_real = self.encoder(batch)  # [B, D]
        h_fake = self.encoder(corrupt)
        summary = nn.sigmoid(jnp.mean(h_real, axis=0))  # [D]
        score = lambda h: h @ self.bilinear @ summary  # noqa: E731
        logits = jnp.concatenate([score(h_real), score(h_fake)])
        labels = jnp.concatenate(
            [jnp.ones(h_real.shape[0]), jnp.zeros(h_fake.shape[0])]
        )
        loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))
        return h_real, loss, "auc", auc(labels, logits)


def gae_batches(graph, flow, batch_size: int, edge_type: int = -1, rng=None):
    """(src, dst, neg) mini-batch source over sampled edges."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        e = graph.sample_edge(batch_size, edge_type, rng=rng)
        neg = graph.sample_node(batch_size, -1, rng=rng)
        return (flow.query(e[:, 0]), flow.query(e[:, 1]), flow.query(neg))

    return fn


def dgi_batches(graph, flow, batch_size: int, node_type: int = -1, rng=None):
    """(real, corrupted) source: corruption shuffles features across the
    batch (DGI's standard corruption)."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        roots = graph.sample_node(batch_size, node_type, rng=rng)
        mb = flow.query(roots)
        perm_feats = tuple(
            f[rng.permutation(len(f))] for f in mb.feats
        )
        return (mb, mb.replace(feats=perm_feats))

    return fn
