"""Layerwise GCN / FastGCN models over dense per-layer adjacencies
(examples/fastgcn, examples/adaptivegcn parity): aggregation is a dense
[n_l, n_{l+1}] matmul per layer — pure MXU work."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.dataflow.layerwise import LayerwiseBatch
from euler_tpu.nn.metrics import micro_f1


class LayerwiseGCN(nn.Module):
    """h_l = act(A_l · h_{l+1} · W_l ⊕ self) from the deepest layer up."""

    dims: Sequence[int]
    label_dim: int
    activation: str = "relu"

    def setup(self):
        self.denses = [nn.Dense(d) for d in self.dims]
        self.self_denses = [nn.Dense(d, use_bias=False) for d in self.dims]
        self.out = nn.Dense(self.label_dim)

    def embed(self, batch: LayerwiseBatch) -> jnp.ndarray:
        act = getattr(nn, self.activation)
        num_layers = len(batch.adjs)
        assert len(self.dims) == num_layers
        xs = list(batch.feats)
        for layer in range(num_layers):
            dense = self.denses[layer]
            self_dense = self.self_denses[layer]
            last = layer == num_layers - 1
            new_xs = []
            for lv in range(num_layers - layer):
                h = dense(batch.adjs[lv] @ xs[lv + 1]) + self_dense(xs[lv])
                if not last:
                    h = act(h)
                h = h * batch.masks[lv][: h.shape[0], None]
                new_xs.append(h)
            xs = new_xs
        return xs[0]

    def __call__(self, batch: LayerwiseBatch):
        emb = self.embed(batch)
        logits = self.out(emb)
        loss = optax.sigmoid_binary_cross_entropy(logits, batch.labels)
        loss = jnp.mean(jnp.sum(loss, axis=-1))
        return emb, loss, "f1", micro_f1(batch.labels, logits)
