"""Knowledge-graph embedding models: TransE/H/R/D, DistMult, RotatE
(examples/TransX, examples/distmult parity).

Entity/relation tables are sharded Embeddings; scoring is batched vector
math (negatives scored via einsum → MXU). Trans* use margin ranking loss
over corrupted triples like the reference; DistMult/RotatE use logistic
loss. Metrics: MRR + hit@10 over the in-batch negatives (the reference
evaluates MeanRank/Hit@10 over full entity ranking at eval time —
see Estimator.evaluate with kg_eval_batches for that path).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from euler_tpu.nn.encoders import Embedding
from euler_tpu.nn.metrics import mrr


def _l2norm(x, axis=-1, eps=1e-12):
    return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), eps)


class TransX(nn.Module):
    """variant ∈ {transe, transh, transr, transd, distmult, rotate}.

    Batch: dict(h, r, t int32[B]; neg_h, neg_t int32[B, N]).
    """

    num_entities: int
    num_relations: int
    dim: int = 100
    rel_dim: int = 0  # transr/transd relation space (defaults to dim)
    variant: str = "transe"
    margin: float = 1.0
    norm_ord: int = 2  # L1 or L2 distance for trans*

    def setup(self):
        rd = self.rel_dim or self.dim
        self.entity = Embedding(self.num_entities + 1, self.dim)
        if self.variant == "rotate":
            self.relation = Embedding(self.num_relations + 1, self.dim // 2)
        else:
            self.relation = Embedding(self.num_relations + 1, rd)
        if self.variant == "transh":
            self.norm_vec = Embedding(self.num_relations + 1, self.dim)
        elif self.variant == "transr":
            # identity-initialized projection: with warm-started tables
            # (transx_warm_start) step 0 then scores exactly as the
            # trained TransE — the published TransR recipe (train TransE
            # first, initialize TransR from it). Random projections were
            # measured to scramble the geometry on the quality stand-in:
            # MR 510-699 across lr sweeps vs 320 staged.
            import numpy as _np

            eye = _np.eye(self.dim, rd, dtype=_np.float32).reshape(-1)

            def _eye_init(key, shape, dtype=jnp.float32):
                del key
                return jnp.broadcast_to(jnp.asarray(eye, dtype), shape)

            self.proj = Embedding(
                self.num_relations + 1, self.dim * rd, row_init=_eye_init
            )
        elif self.variant == "transd":
            # zero-initialized projection vectors: h⊥ = h + (hp·h)rp
            # reduces to TransE at step 0 (same recipe as TransR)
            self.ent_proj = Embedding(
                self.num_entities + 1, self.dim,
                row_init=nn.initializers.zeros,
            )
            self.rel_proj = Embedding(
                self.num_relations + 1, rd,
                row_init=nn.initializers.zeros,
            )

    def embed(self, ids: jnp.ndarray) -> jnp.ndarray:
        return self.entity(ids)

    # -- scoring ---------------------------------------------------------

    def _project(self, e, e_ids, r_ids):
        """Entity → relation space, per variant."""
        rd = self.rel_dim or self.dim
        if self.variant == "transh":
            w = _l2norm(self.norm_vec(r_ids))
            w = w.reshape(e.shape)  # broadcast negs
            return e - jnp.sum(w * e, axis=-1, keepdims=True) * w
        if self.variant == "transr":
            m = self.proj(r_ids).reshape(r_ids.shape + (self.dim, rd))
            return jnp.einsum("...d,...dk->...k", e, m)
        if self.variant == "transd":
            ep = self.ent_proj(e_ids)
            rp = self.rel_proj(r_ids)
            inner = jnp.sum(ep * e, axis=-1, keepdims=True)
            pad = rd - self.dim
            base = e if pad <= 0 else jnp.pad(e, [(0, 0)] * (e.ndim - 1) + [(0, pad)])
            return base[..., :rd] + inner * rp
        return e

    def _score(self, h, r, t, h_ids, r_ids, t_ids):
        """Higher = more plausible."""
        if self.variant == "distmult":
            return jnp.sum(h * r * t, axis=-1)
        if self.variant == "rotate":
            hr, hi = jnp.split(h, 2, axis=-1)
            tr, ti = jnp.split(t, 2, axis=-1)
            cr, ci = jnp.cos(r), jnp.sin(r)
            dr = hr * cr - hi * ci - tr
            di = hr * ci + hi * cr - ti
            return -jnp.sum(jnp.sqrt(dr**2 + di**2 + 1e-12), axis=-1)
        hp = self._project(h, h_ids, r_ids)
        tp = self._project(t, t_ids, r_ids)
        if self.variant == "transd":
            # the reference l2-normalizes entities AFTER projecting into
            # relation space (transD.py:53) — without it projected norms
            # drift and the margin loss degenerates (measured on the
            # quality stand-in: MR 381 → 250, Hit@10 0.318 → 0.382)
            hp, tp = _l2norm(hp), _l2norm(tp)
        diff = hp + r - tp
        if self.norm_ord == 1:
            return -jnp.sum(jnp.abs(diff), axis=-1)
        return -jnp.sqrt(jnp.sum(diff**2, axis=-1) + 1e-12)

    def score_triples(self, h_ids, r_ids, t_ids):
        h = self.entity(h_ids)
        t = self.entity(t_ids)
        r = self.relation(r_ids)
        if self.variant in ("transe", "transh", "transr"):
            # transr normalizes BEFORE its (identity-initialized)
            # projection: step 0 is then exactly TransE and training
            # learns per-relation deviations from that geometry —
            # post-projection norm or a normalized offset were both
            # measured substantially worse on the quality stand-in
            h, t = _l2norm(h), _l2norm(t)
        if self.variant == "transd":
            # norm_emb on relations (transX.py:63-66): keeps the relation
            # offset on the same scale as the unit-normalized projections.
            # TransR keeps the raw offset — with identity-initialized
            # projections its geometry starts as TransE's, whose offsets
            # are unnormalized; clamping them to unit length was measured
            # to collapse Hit@10 (0.27 → 0.04) on the quality stand-in.
            r = _l2norm(r)
        return self._score(h, r, t, h_ids, r_ids, t_ids)

    # -- training --------------------------------------------------------

    def __call__(self, batch):
        h, r, t = batch["h"], batch["r"], batch["t"]
        neg_h, neg_t = batch["neg_h"], batch["neg_t"]
        b, n = neg_h.shape
        pos = self.score_triples(h, r, t)  # [B]
        r2 = jnp.broadcast_to(r[:, None], (b, n))
        neg1 = self.score_triples(neg_h, r2, jnp.broadcast_to(t[:, None], (b, n)))
        neg2 = self.score_triples(jnp.broadcast_to(h[:, None], (b, n)), r2, neg_t)
        negs = jnp.concatenate([neg1, neg2], axis=1)  # [B, 2N]
        if self.variant in ("distmult", "rotate"):
            loss = jnp.mean(nn.softplus(-pos)) + jnp.mean(nn.softplus(negs))
        else:
            loss = jnp.mean(
                nn.relu(self.margin + negs - pos[:, None])
            )
        return self.entity(h), loss, "mrr", mrr(pos, negs)


def transx_warm_start(model, trained_params, example_batch, seed: int = 0):
    """Warm-start params for a projection variant from a trained sibling.

    The published TransR protocol trains TransE first and initializes
    TransR's entity/relation tables from it (the projections start at
    identity/zero via this module's initializers, so step 0 scores exactly
    match the trained TransE). Returns an unboxed params pytree for
    Estimator(init_params=...)."""
    import flax.linen as fnn
    import jax as _jax

    p = fnn.meta.unbox(
        model.init(_jax.random.PRNGKey(seed), example_batch)
    )
    p = _jax.tree_util.tree_map(lambda x: x, p)
    for name in ("entity", "relation"):
        p["params"][name]["table"] = trained_params["params"][name]["table"]
    return p


def kg_batches(
    graph, batch_size: int, num_negs: int = 8, edge_type: int = -1, rng=None
):
    """Triple source: sampled edges (h=src, r=type, t=dst) + corrupted
    heads/tails drawn from the global node sampler."""
    rng = rng if rng is not None else np.random.default_rng()

    def to32(x):
        return x.astype(np.int64).astype(np.int32)

    def fn():
        e = graph.sample_edge(batch_size, edge_type, rng=rng)
        negs = graph.sample_node(batch_size * num_negs * 2, -1, rng=rng)
        negs = to32(negs).reshape(2, batch_size, num_negs)
        return (
            {
                "h": to32(e[:, 0]),
                "r": to32(e[:, 2]),
                "t": to32(e[:, 1]),
                "neg_h": negs[0],
                "neg_t": negs[1],
            },
        )

    return fn


def kg_ranking_metrics(
    model,
    params,
    triples: np.ndarray,
    num_entities: int,
    filter_triples: np.ndarray | None = None,
    batch: int = 64,
    sides: tuple = ("head", "tail"),
):
    """Full-ranking evaluation with the FILTERED setting (Bordes et al.):
    MRR, Hits@1/10 and MeanRank over head- and tail-corrupted triples,
    with every OTHER known-true triple removed from the candidate list
    before ranking (raw setting when ``filter_triples`` is None — a
    plausible corruption that happens to be a real edge then counts as a
    negative, deflating the metrics).

    triples / filter_triples: int [M, 3] (h, r, t); entities are 1-based
    ids into the model's entity table. Pass the training edge set as
    ``filter_triples`` (the analytics sweep runner hands over the
    pinned-epoch triple list). Deterministic: pure scoring, no sampling.
    """
    import jax

    triples = np.asarray(triples, np.int64)
    all_ents = jnp.arange(1, num_entities + 1, dtype=jnp.int32)

    @jax.jit
    def scores_for(h, r, t, corrupt_head):
        pos = model.apply(
            params, h.astype(jnp.int32), r.astype(jnp.int32),
            t.astype(jnp.int32), method=model.score_triples,
        )
        b = h.shape[0]
        ents = jnp.broadcast_to(all_ents[None, :], (b, num_entities))
        rb = jnp.broadcast_to(r[:, None].astype(jnp.int32), ents.shape)
        fixed = jnp.where(corrupt_head, t, h)
        fixed = jnp.broadcast_to(fixed[:, None].astype(jnp.int32), ents.shape)
        negs = jnp.where(
            corrupt_head,
            model.apply(params, ents, rb, fixed, method=model.score_triples),
            model.apply(params, fixed, rb, ents, method=model.score_triples),
        )
        return pos, negs

    known = None
    if filter_triples is not None:
        known = np.unique(
            _triple_keys(np.asarray(filter_triples, np.int64), num_entities)
        )
    ranks = []
    ent_range = np.arange(1, num_entities + 1, dtype=np.int64)
    for side in sides:
        corrupt_head = side == "head"
        for i in range(0, len(triples), batch):
            chunk = triples[i:i + batch]
            h = jnp.asarray(chunk[:, 0], jnp.int32)
            r = jnp.asarray(chunk[:, 1], jnp.int32)
            t = jnp.asarray(chunk[:, 2], jnp.int32)
            pos, negs = scores_for(h, r, t, corrupt_head)
            pos = np.asarray(pos, np.float64)
            negs = np.asarray(negs, np.float64)
            beat = negs > pos[:, None]
            if known is not None:
                # filtered setting: a candidate that forms ANOTHER true
                # triple is no negative at all — drop it from the count
                b = len(chunk)
                if corrupt_head:
                    cand = np.stack([
                        np.broadcast_to(ent_range, (b, num_entities)),
                        np.broadcast_to(chunk[:, 1:2], (b, num_entities)),
                        np.broadcast_to(chunk[:, 2:3], (b, num_entities)),
                    ], axis=-1)
                else:
                    cand = np.stack([
                        np.broadcast_to(chunk[:, 0:1], (b, num_entities)),
                        np.broadcast_to(chunk[:, 1:2], (b, num_entities)),
                        np.broadcast_to(ent_range, (b, num_entities)),
                    ], axis=-1)
                is_known = np.isin(
                    _triple_keys(cand.reshape(-1, 3), num_entities), known
                ).reshape(b, num_entities)
                beat &= ~is_known
            ranks.append(1 + beat.sum(axis=1))
    ranks = np.concatenate(ranks).astype(np.float64)
    return {
        "mean_rank": float(ranks.mean()),
        "mrr": float((1.0 / ranks).mean()),
        "hit@1": float((ranks <= 1).mean()),
        "hit@10": float((ranks <= 10).mean()),
        "filtered": filter_triples is not None,
        "num_ranks": int(len(ranks)),
    }


_REL_BASE = np.int64(1) << 20  # relation-id radix of the triple key


def _triple_keys(triples: np.ndarray, num_entities: int) -> np.ndarray:
    """Collision-free int64 key per (h, r, t) row: entity slots are
    1-based and bounded by num_entities, relation ids by 2^20. The same
    radices encode eval candidates and the filter set, so membership is
    a plain sorted-array isin."""
    t = np.asarray(triples, np.int64)
    ent_base = np.int64(num_entities + 2)
    return (t[:, 0] * ent_base + t[:, 2]) * _REL_BASE + t[:, 1]


def kg_rank_eval(model, params, triples: np.ndarray, num_entities: int, batch: int = 64):
    """Full-ranking eval: MeanRank / MRR / Hit@10 against ALL entities
    (examples/TransX README metric). triples: int32 [M, 3] (h, r, t)."""
    import jax

    all_ents = jnp.arange(1, num_entities + 1, dtype=jnp.int32)

    @jax.jit
    def scores_for(h, r, t):
        pos = model.apply(params, h, r, t, method=model.score_triples)
        b = h.shape[0]
        ents = jnp.broadcast_to(all_ents[None, :], (b, num_entities))
        rb = jnp.broadcast_to(r[:, None], (b, num_entities))
        neg_t = model.apply(
            params,
            jnp.broadcast_to(h[:, None], (b, num_entities)),
            rb,
            ents,
            method=model.score_triples,
        )
        return pos, neg_t

    ranks = []
    for i in range(0, len(triples), batch):
        chunk = triples[i : i + batch]
        h = jnp.asarray(chunk[:, 0])
        r = jnp.asarray(chunk[:, 1])
        t = jnp.asarray(chunk[:, 2])
        pos, negs = scores_for(h, r, t)
        ranks.append(
            np.asarray(
                1
                + jnp.sum((negs > pos[:, None]).astype(jnp.int32), axis=1)
            )
        )
    ranks = np.concatenate(ranks).astype(np.float64)
    return {
        "mean_rank": float(ranks.mean()),
        "mrr": float((1.0 / ranks).mean()),
        "hit@10": float((ranks <= 10).mean()),
    }
