"""Ring-parallel full-graph GCN — whole-graph training sharded over a mesh
axis (nodes, edges, AND activations partitioned).

The reference's full-graph models (tf_euler whole-graph GCN path,
examples/gcn) hold the entire Â and activation matrices on one device;
this model is the long-context analog: node rows and edge buckets shard
over the `model` axis and every propagation runs
`parallel.sp.ring_segment_sum` — a P-step ppermute ring identical in
schedule to ring attention. Per-device memory is O(N/P·F + E/P); nothing
ever materializes [N, F] or [E, F] on one device.

Usage (see tests/test_sp_ring.py for the full parity harness):

    buckets, ids = bucket_full_graph(graph, parts=mesh.shape['model'])
    model = SPFullGraphGCN(dims=[64, 64], label_dim=C)
    dev_buckets, x = put_ring(mesh, buckets, features_of(ids))
    logits = model.apply(params, x, dev_buckets, mesh)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.parallel.sp import ring_segment_sum


class SPFullGraphGCN(nn.Module):
    """GCN stack where every Â·H propagation is a ring pass.

    dims: hidden widths per layer; label_dim: classifier width.
    The GCN normalization lives in the bucket weights
    (`bucket_full_graph(..., norm='gcn')`), so each layer is exactly
    ring(Â) → dense → relu, and the head is a dense classifier on the
    (row-sharded) final features.
    """

    dims: tuple | list
    label_dim: int

    @nn.compact
    def __call__(self, x, buckets, mesh, axis: str = "model"):
        h = x
        for d in self.dims:
            h = ring_segment_sum(h, buckets, mesh, axis)
            h = nn.Dense(d)(h)
            h = nn.relu(h)
        return nn.Dense(self.label_dim)(h)


def masked_softmax_xent(logits, labels_onehot, mask):
    """Mean cross-entropy over mask=True rows (padded rows contribute 0).

    logits/labels row-sharded the same way; the mean is a global scalar
    (jnp reductions over sharded arrays produce the full reduction).
    """
    logp = jax.nn.log_softmax(logits)
    per_row = -jnp.sum(labels_onehot * logp, axis=-1)
    m = mask.astype(per_row.dtype)
    return jnp.sum(per_row * m) / jnp.maximum(jnp.sum(m), 1.0)
