"""Scalable (history-embedding) GCN/SAGE training
(utils/encoders.py:294-410, 629-750 parity).

Each layer keeps a host-side HistoryTable of its last activations; a train
step touches only roots + their 1-hop neighbors, reading deeper context from
the tables and refreshing the roots' rows with a moving average. Receptive
field per step is 1 hop regardless of depth — the GAS-style scalability
trick, with the PS variable store replaced by host numpy tables.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.nn.history import HistoryTable
from euler_tpu.nn.metrics import micro_f1


class ScalableGNN(nn.Module):
    """K stacked mean-aggregator layers over history inputs.

    Batch dict: feats f32[B,F]; nbr_hist tuple of f32[B,k,D_l] (layer l's
    neighbor activations from history; l=0 uses raw neighbor features);
    nbr_mask bool[B,k]; labels f32[B,L].
    """

    dims: Sequence[int]
    label_dim: int

    def setup(self):
        self.layers = [nn.Dense(d) for d in self.dims]
        self.self_layers = [nn.Dense(d, use_bias=False) for d in self.dims]
        self.out = nn.Dense(self.label_dim)

    def activations(self, batch) -> list[jnp.ndarray]:
        h = batch["feats"]
        m = batch["nbr_mask"].astype(jnp.float32)[..., None]
        acts = []
        for i, (lin, self_lin) in enumerate(
            zip(self.layers, self.self_layers)
        ):
            nbr = batch["nbr_hist"][i]
            agg = jnp.sum(nbr * m, axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
            h = lin(agg) + self_lin(h)
            if i < len(self.layers) - 1:
                h = nn.relu(h)
            acts.append(h)
        return acts

    def embed(self, batch) -> jnp.ndarray:
        return self.activations(batch)[-1]

    def __call__(self, batch):
        acts = self.activations(batch)
        logits = self.out(acts[-1])
        loss = optax.sigmoid_binary_cross_entropy(logits, batch["labels"])
        loss = jnp.mean(jnp.sum(loss, axis=-1))
        return acts, loss, "f1", micro_f1(batch["labels"], logits)


class ScalableTrainer:
    """1-hop train loop with history fetch/update around a jitted step."""

    def __init__(
        self,
        graph,
        model: ScalableGNN,
        feature_names,
        max_id: int,
        batch_size: int = 64,
        fanout: int = 10,
        edge_types=None,
        label_feature: str = "label",
        learning_rate: float = 0.01,
        momentum: float = 0.9,
        rng=None,
    ):
        self.graph = graph
        self.model = model
        self.feature_names = feature_names
        self.batch_size = batch_size
        self.fanout = fanout
        self.edge_types = edge_types
        self.label_feature = label_feature
        self.rng = rng if rng is not None else np.random.default_rng()
        feat_dim = graph.get_dense_feature(
            np.asarray([1], np.uint64), feature_names
        ).shape[1]
        self.feat_dim = feat_dim
        self.histories = [
            HistoryTable(max_id, d, momentum)
            for d in [feat_dim] + list(model.dims[:-1])
        ]
        self.tx = optax.adam(learning_rate)
        self.params = None
        self.opt_state = None
        self._step = None

    def _make_batch(self):
        g = self.graph
        roots = g.sample_node(self.batch_size, -1, rng=self.rng)
        nbr, _, _, mask, _ = g.sample_neighbor(
            roots, self.edge_types, self.fanout, rng=self.rng
        )
        flat = nbr.reshape(-1)
        k = self.fanout
        nbr_hist = []
        for li, h in enumerate(self.histories):
            if li == 0:
                vals = g.get_dense_feature(flat, self.feature_names)
            else:
                vals = h.fetch(flat)
            nbr_hist.append(
                vals.reshape(self.batch_size, k, -1).astype(np.float32)
            )
        return roots, {
            "feats": g.get_dense_feature(roots, self.feature_names),
            "nbr_hist": tuple(nbr_hist),
            "nbr_mask": mask,
            "labels": g.get_dense_feature(roots, [self.label_feature]),
        }

    def train(self, steps: int):
        history = []
        for _ in range(steps):
            roots, batch = self._make_batch()
            if self.params is None:
                self.params = self.model.init(jax.random.PRNGKey(0), batch)
                self.opt_state = self.tx.init(self.params)

                @jax.jit
                def step(params, opt_state, batch):
                    def loss_fn(p):
                        acts, loss, _, metric = self.model.apply(p, batch)
                        return loss, (acts, metric)

                    (loss, (acts, metric)), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params)
                    updates, opt_state = self.tx.update(
                        grads, opt_state, params
                    )
                    return (
                        optax.apply_updates(params, updates),
                        opt_state,
                        loss,
                        acts,
                    )

                self._step = step
            self.params, self.opt_state, loss, acts = self._step(
                self.params, self.opt_state, batch
            )
            # refresh histories: layer l+1's input table holds layer l output
            for li in range(1, len(self.histories)):
                self.histories[li].update(roots, np.asarray(acts[li - 1]))
            history.append(float(loss))
        return history
