"""Shallow embedding models: DeepWalk / node2vec / LINE
(examples/deepwalk, examples/line parity).

All are target/context embedding tables trained with sampled-softmax
negative sampling; tables are sharded over the 'model' mesh axis. The walk
and pair generation run host-side (euler_tpu.dataflow.walk); the device step
is pure embedding math — gathers + batched dot products on the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.dataflow.walk import gen_pair
from euler_tpu.nn.encoders import Embedding
from euler_tpu.nn.metrics import mrr


class SkipGramModel(nn.Module):
    """Target/context tables + sampled softmax (DeepWalk & LINE-2nd).

    Batch: dict(src int32[B], pos int32[B], negs int32[B, N], mask bool[B]).
    """

    num_nodes: int
    dim: int = 128
    shared_context: bool = False  # True → LINE first-order (one table)

    def setup(self):
        self.target = Embedding(self.num_nodes + 1, self.dim)
        if not self.shared_context:
            self.ctx_table = Embedding(self.num_nodes + 1, self.dim)

    def embed(self, ids: jnp.ndarray) -> jnp.ndarray:
        return self.target(ids)

    def _ctx(self, ids):
        return self.target(ids) if self.shared_context else self.ctx_table(ids)

    def __call__(self, batch):
        src, pos, negs = batch["src"], batch["pos"], batch["negs"]
        mask = batch["mask"].astype(jnp.float32)
        e_src = self.target(src)  # [B, D]
        e_pos = self._ctx(pos)  # [B, D]
        e_neg = self._ctx(negs)  # [B, N, D]
        pos_logit = jnp.sum(e_src * e_pos, axis=-1)
        neg_logit = jnp.einsum("bd,bnd->bn", e_src, e_neg)
        logits = jnp.concatenate([pos_logit[:, None], neg_logit], axis=1)
        labels = jnp.zeros(src.shape[0], dtype=jnp.int32)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        loss = jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return e_src, loss, "mrr", mrr(pos_logit, neg_logit)


def deepwalk_batches(
    graph,
    batch_size: int,
    walk_len: int = 5,
    window: int = 2,
    num_negs: int = 5,
    edge_types=None,
    p: float = 1.0,
    q: float = 1.0,
    node_type: int = -1,
    rng=None,
):
    """Walk → skipgram pairs → (src, pos, negs, mask) batch source.

    p/q ≠ 1 gives node2vec biased walks (random_walk_op.cc:27-90).
    """
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        roots = graph.sample_node(batch_size, node_type, rng=rng)
        walks = graph.random_walk(
            roots, edge_types, walk_len=walk_len, p=p, q=q, rng=rng
        )
        pairs, mask = gen_pair(walks, window, window)
        negs = graph.sample_node(len(pairs) * num_negs, node_type, rng=rng)
        return (
            {
                "src": pairs[:, 0].astype(np.int64).astype(np.int32),
                "pos": pairs[:, 1].astype(np.int64).astype(np.int32),
                "negs": negs.astype(np.int64)
                .astype(np.int32)
                .reshape(len(pairs), num_negs),
                "mask": mask,
            },
        )

    return fn


def line_batches(
    graph,
    batch_size: int,
    num_negs: int = 5,
    edge_type: int = -1,
    rng=None,
):
    """Edge-sampling batch source for LINE (examples/line)."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        edges = graph.sample_edge(batch_size, edge_type, rng=rng)
        negs = graph.sample_node(batch_size * num_negs, -1, rng=rng)
        return (
            {
                "src": edges[:, 0].astype(np.int64).astype(np.int32),
                "pos": edges[:, 1].astype(np.int64).astype(np.int32),
                "negs": negs.astype(np.int64)
                .astype(np.int32)
                .reshape(batch_size, num_negs),
                "mask": np.ones(batch_size, dtype=bool),
            },
        )

    return fn
