"""RGCN over per-relation blocks (examples/rgcn parity)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.dataflow.relation import RelMiniBatch
from euler_tpu.layers import RelationConv
from euler_tpu.nn.metrics import micro_f1


class RGCNSupervised(nn.Module):
    dims: Sequence[int]
    num_relations: int
    label_dim: int
    num_bases: int = 0
    activation: str = "relu"

    def setup(self):
        self.convs = [
            RelationConv(
                out_dim=d,
                num_relations=self.num_relations,
                num_bases=self.num_bases,
            )
            for d in self.dims
        ]
        self.out = nn.Dense(self.label_dim)

    def embed(self, batch: RelMiniBatch) -> jnp.ndarray:
        act = getattr(nn, self.activation)
        num_hops = len(batch.rel_blocks)
        xs = list(batch.feats)
        for layer in range(num_hops):
            conv = self.convs[layer]
            last = layer == num_hops - 1
            new_xs = []
            for hop in range(num_hops - layer):
                h = conv(xs[hop], xs[hop + 1], batch.rel_blocks[hop])
                if not last:
                    h = act(h)
                h = h * batch.masks[hop][: h.shape[0], None]
                new_xs.append(h)
            xs = new_xs
        return xs[0]

    def __call__(self, batch: RelMiniBatch):
        emb = self.embed(batch)
        logits = self.out(emb)
        loss = optax.sigmoid_binary_cross_entropy(logits, batch.labels)
        loss = jnp.mean(jnp.sum(loss, axis=-1))
        return emb, loss, "f1", micro_f1(batch.labels, logits)
