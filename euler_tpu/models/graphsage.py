"""GraphSAGE — the flagship model family (examples/graphsage parity).

Supervised and unsupervised variants over sampled-fanout dataflows, with an
optional ShallowEncoder input stage (id embedding sharded over the 'model'
mesh axis + dense-feature projection), matching the reference's
GraphSageEncoder composition (examples/graphsage/graphsage.py +
utils/encoders.py SageEncoder).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.dataflow.base import MiniBatch
from euler_tpu.nn.base_gnn import GNNNet
from euler_tpu.nn.encoders import ShallowEncoder
from euler_tpu.nn.metrics import micro_f1, mrr


class _EncodedGNN(nn.Module):
    """ShallowEncoder applied per hop, then the conv stack."""

    conv: str
    dims: Sequence[int]
    encoder_dim: int = 0  # 0 → raw features
    max_id: int = 0
    conv_kwargs: dict | None = None
    remat: bool = False  # rematerialize conv layers (GNNNet.remat)

    def setup(self):
        if self.encoder_dim:
            self.encoder = ShallowEncoder(
                dim=self.encoder_dim, max_id=self.max_id
            )
        self.gnn = GNNNet(
            conv=self.conv, dims=self.dims, conv_kwargs=self.conv_kwargs,
            remat=self.remat,
        )

    def __call__(self, batch: MiniBatch) -> jnp.ndarray:
        if not self.encoder_dim:
            return self.gnn(batch)
        ids = batch.hop_ids or (None,) * len(batch.feats)
        feats = tuple(
            self.encoder(
                ids=i if self.max_id else None, dense=f
            )
            for i, f in zip(ids, batch.feats)
        )
        return self.gnn(batch.replace(feats=feats))


class GraphSAGESupervised(nn.Module):
    dims: Sequence[int]
    label_dim: int
    encoder_dim: int = 0
    max_id: int = 0
    conv: str = "sage"
    conv_kwargs: dict | None = None
    remat: bool = False

    def setup(self):
        self.net = _EncodedGNN(
            conv=self.conv,
            dims=self.dims,
            encoder_dim=self.encoder_dim,
            max_id=self.max_id,
            conv_kwargs=self.conv_kwargs,
            remat=self.remat,
        )
        self.out = nn.Dense(self.label_dim)

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        return self.net(batch)

    def __call__(self, batch: MiniBatch):
        emb = self.embed(batch)
        logits = self.out(emb)
        loss = optax.sigmoid_binary_cross_entropy(logits, batch.labels)
        loss = jnp.mean(jnp.sum(loss, axis=-1))
        return emb, loss, "f1", micro_f1(batch.labels, logits)


class GraphSAGEUnsupervised(nn.Module):
    dims: Sequence[int]
    encoder_dim: int = 0
    max_id: int = 0
    conv: str = "sage"
    conv_kwargs: dict | None = None
    remat: bool = False

    def setup(self):
        self.net = _EncodedGNN(
            conv=self.conv,
            dims=self.dims,
            encoder_dim=self.encoder_dim,
            max_id=self.max_id,
            conv_kwargs=self.conv_kwargs,
            remat=self.remat,
        )

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        return self.net(batch)

    def __call__(self, src: MiniBatch, pos: MiniBatch, negs: MiniBatch):
        e_src = self.embed(src)
        e_pos = self.embed(pos)
        e_neg = self.embed(negs)
        b, d = e_src.shape
        e_neg = e_neg.reshape(b, -1, d)
        pos_logit = jnp.sum(e_src * e_pos, axis=-1)
        neg_logit = jnp.einsum("bd,bnd->bn", e_src, e_neg)
        logits = jnp.concatenate([pos_logit[:, None], neg_logit], axis=1)
        labels = jnp.zeros(b, dtype=jnp.int32)
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        return e_src, loss, "mrr", mrr(pos_logit, neg_logit)
