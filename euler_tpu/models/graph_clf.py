"""Whole-graph classifiers (examples/gin, set2set, gated_graph, graphgcn
parity): conv stack over the batched node table → graph pooling → softmax
head with accuracy metric (mp_utils/base_graph.py:24-47)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.dataflow.whole import GraphBatch
from euler_tpu.layers import get_conv
from euler_tpu.nn.metrics import accuracy
from euler_tpu.nn.pooling import AttentionPool, Pooling, Set2SetPool


class GraphClassifier(nn.Module):
    conv: str = "gin"
    dims: Sequence[int] = (32, 32)
    num_classes: int = 2
    pool: str = "mean"  # add | mean | max | attention | set2set
    activation: str = "relu"
    remat: bool = False  # rematerialize conv layers (see GNNNet.remat)

    def setup(self):
        cls = get_conv(self.conv)
        if self.remat:
            cls = nn.remat(cls, static_argnums=())
        self.convs = [cls(out_dim=d) for d in self.dims]
        if self.pool == "attention":
            self.pooler = AttentionPool()
        elif self.pool == "set2set":
            self.pooler = Set2SetPool()
        else:
            self.pooler = Pooling(op=self.pool)
        self.head = nn.Dense(self.num_classes)

    def embed(self, batch: GraphBatch) -> jnp.ndarray:
        act = getattr(nn, self.activation)
        x = batch.feats
        for i, conv in enumerate(self.convs):
            x = conv(x, x, batch.block)
            if i < len(self.convs) - 1:
                x = act(x)
            x = x * batch.node_mask[:, None]
        return self.pooler(
            x, batch.graph_ids, batch.n_graphs, mask=batch.node_mask
        )

    def __call__(self, batch: GraphBatch):
        emb = self.embed(batch)
        logits = self.head(emb)
        labels = jnp.argmax(batch.labels, axis=-1)
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        preds = jnp.argmax(logits, axis=-1)
        return emb, loss, "acc", accuracy(labels, preds)
