from euler_tpu.models.embedding_models import (  # noqa: F401
    SkipGramModel,
    deepwalk_batches,
    line_batches,
)
from euler_tpu.models.graphsage import (  # noqa: F401
    GraphSAGESupervised,
    GraphSAGEUnsupervised,
)
from euler_tpu.models.graph_clf import GraphClassifier  # noqa: F401
from euler_tpu.models.kg import TransX, kg_batches, kg_rank_eval  # noqa: F401
