from euler_tpu.models.embedding_models import (  # noqa: F401
    SkipGramModel,
    deepwalk_batches,
    line_batches,
)
from euler_tpu.models.graphsage import (  # noqa: F401
    GraphSAGESupervised,
    GraphSAGEUnsupervised,
)
from euler_tpu.models.graph_clf import GraphClassifier  # noqa: F401
from euler_tpu.models.kg import (  # noqa: F401
    TransX,
    kg_batches,
    kg_rank_eval,
    kg_ranking_metrics,
    transx_warm_start,
)
from euler_tpu.models.layerwise_models import LayerwiseGCN  # noqa: F401
from euler_tpu.models.rgcn import RGCNSupervised  # noqa: F401
from euler_tpu.models.autoencoders import DGI, GAE, dgi_batches, gae_batches  # noqa: F401
from euler_tpu.models.scalable import ScalableGNN, ScalableTrainer  # noqa: F401
