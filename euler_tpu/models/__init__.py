from euler_tpu.models.graphsage import (  # noqa: F401
    GraphSAGESupervised,
    GraphSAGEUnsupervised,
)
