"""Device-side message-passing primitives.

The TPU equivalent of the reference's MPGather/MPScatter* TF custom ops
(tf_euler/python/euler_ops/mp_ops.py:27-79, tf_euler/kernels/scatter_op.cc).
Everything is expressed over *static-shape* segment operations so XLA can fuse
the gather → elementwise → segment-reduce chain into the surrounding matmuls.

Padding convention: dataflows route padded edges to valid-looking indices and
pass `mask`; masked lanes contribute the reduction identity (0 for add/mean,
-inf for max, zero probability for softmax).

Gradient parity with the reference:
  - gather ↔ scatter_add adjoints (mp_ops.py:39-49)
  - scatter_max splits the subgradient equally among argmax ties
    (mp_ops.py:52-62)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def gather(params: Array, indices: Array) -> Array:
    """params[indices] along axis 0 (MPGather)."""
    return jnp.take(params, indices, axis=0)


def _masked(data: Array, mask: Array | None, fill) -> Array:
    if mask is None:
        return data
    shape = mask.shape + (1,) * (data.ndim - mask.ndim)
    return jnp.where(mask.reshape(shape), data, fill)


def scatter_add(
    data: Array,
    segment_ids: Array,
    num_segments: int,
    mask: Array | None = None,
) -> Array:
    """Sum `data` rows into `num_segments` rows (MPScatterAdd)."""
    return jax.ops.segment_sum(
        _masked(data, mask, 0), segment_ids, num_segments=num_segments
    )


def scatter_mean(
    data: Array,
    segment_ids: Array,
    num_segments: int,
    mask: Array | None = None,
) -> Array:
    """Segment mean; empty segments yield 0 (scatter_mean, mp_ops.py:65-69)."""
    total = scatter_add(data, segment_ids, num_segments, mask)
    ones = jnp.ones(data.shape[:1], dtype=data.dtype)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
    count = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    count = jnp.maximum(count, 1)
    return total / count.reshape((num_segments,) + (1,) * (data.ndim - 1))


@jax.custom_vjp
def _segment_max(data, segment_ids, num_segments):
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


def _segment_max_fwd(data, segment_ids, num_segments):
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    return out, (data, segment_ids, num_segments, out)


def _segment_max_bwd(res, g):
    data, segment_ids, num_segments, out = res
    picked = gather(out, segment_ids)
    ties = (data == picked).astype(data.dtype)
    counts = jax.ops.segment_sum(ties, segment_ids, num_segments=num_segments)
    counts = jnp.maximum(counts, 1)
    # equal split among argmax ties (scatter_op.cc:66-78 / mp_ops.py:52-62)
    dd = ties * gather(g / counts, segment_ids)
    return dd, None, None


_segment_max.defvjp(_segment_max_fwd, _segment_max_bwd)


def scatter_max(
    data: Array,
    segment_ids: Array,
    num_segments: int,
    mask: Array | None = None,
    empty_value: float = 0.0,
) -> Array:
    """Segment max; ties split the gradient equally (MPScatterMax).

    Empty segments produce `empty_value` (the reference fills a large
    negative then replaces; we expose the fill directly).
    """
    neg = jnp.finfo(data.dtype).min
    filled = _masked(data, mask, neg)
    out = _segment_max(filled, segment_ids, num_segments)
    # empty segments surface as -inf (segment_max identity) or as the mask
    # fill; both are <= finfo.min
    return jnp.where(out <= neg, jnp.asarray(empty_value, out.dtype), out)


def scatter_softmax(
    data: Array,
    segment_ids: Array,
    num_segments: int,
    mask: Array | None = None,
) -> Array:
    """Per-segment softmax over rows (scatter_softmax, mp_ops.py:71-79).

    Returns an array shaped like `data`: each row's probability within its
    segment. Masked rows get probability 0.
    """
    neg = jnp.finfo(data.dtype).min
    filled = _masked(data, mask, neg)
    seg_max = jax.ops.segment_max(filled, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(seg_max <= neg, 0.0, seg_max)
    shifted = filled - gather(seg_max, segment_ids)
    expd = jnp.exp(shifted)
    if mask is not None:
        shape = mask.shape + (1,) * (data.ndim - mask.ndim)
        expd = jnp.where(mask.reshape(shape), expd, 0.0)
    denom = jax.ops.segment_sum(expd, segment_ids, num_segments=num_segments)
    denom = jnp.maximum(denom, jnp.finfo(data.dtype).tiny)
    return expd / gather(denom, segment_ids)


def scatter(op: str, data, segment_ids, num_segments, mask=None):
    """Dispatch by name ('add' | 'mean' | 'max' | 'softmax') — the string
    interface the reference's aggregators use (mp_ops.scatter_)."""
    fns = {
        "add": scatter_add,
        "sum": scatter_add,
        "mean": scatter_mean,
        "max": scatter_max,
        "softmax": scatter_softmax,
    }
    return fns[op](data, segment_ids, num_segments, mask=mask)
