from euler_tpu.ops import mp_ops  # noqa: F401
from euler_tpu.ops.mp_ops import (  # noqa: F401
    gather,
    scatter,
    scatter_add,
    scatter_max,
    scatter_mean,
    scatter_softmax,
)
