import os

from euler_tpu.ops import mp_ops  # noqa: F401
from euler_tpu.ops.mp_ops import (  # noqa: F401
    gather,
    scatter,
    scatter_add,
    scatter_max,
    scatter_mean,
    scatter_softmax,
)
from euler_tpu.ops.pallas_kernels import gather_weighted_sum  # noqa: F401

# 'off' → pure XLA segment ops; 'auto' → fused Pallas kernel on TPU;
# 'interpret' → Pallas interpreter (testing)
_PALLAS_MODE = os.environ.get("EULER_TPU_PALLAS", "off")


def set_pallas(mode: str) -> None:
    global _PALLAS_MODE
    assert mode in ("off", "auto", "interpret", "pallas")
    _PALLAS_MODE = mode


def pallas_mode() -> str:
    return _PALLAS_MODE
