"""Pallas TPU kernels for the message-passing hot path.

`gather_weighted_sum(x, slots, w)` fuses the neighbor gather with the
weighted segment reduction: out[i] = Σ_j w[i, j] · x[slots[i, j]].

Every euler_tpu dataflow emits *grid-structured* blocks (each dst row owns a
fixed strip of D neighbor slots), so the aggregation is this one primitive —
it subsumes SAGE-mean (w = mask/deg), GCN (w = norm products), and weighted
sums, without materializing the [E, F] message tensor in HBM. The kernel
keeps the feature table in HBM, DMA-gathers each row's D neighbor vectors
into VMEM scratch, and reduces them with a (1×D)·(D×F) matmul on the MXU.

Backward is pure JAX (scatter-add of w·g, and g·x for the weights) via
custom_vjp — gradient layout matches mp_ops (reference mp_ops.py:39-62).

CPU/interpret fallback makes the same entry point usable in tests.

The paged device-sampling lane (dataflow/device.py, layout="paged") adds
two more entry points with the same impl discipline — `paged_gather`
(ragged neighbor/weight gather through a fixed-size-page indirection,
the Ragged-Paged-Attention access shape) and `paged_cdf_count` (the
in-page step of the two-level quantized-CDF neighbor draw). Both carry a
jitted jnp reference (`impl="xla"`) that is the `auto` fallback off-TPU
and the A/B oracle; the Pallas forms are validated in interpret mode
(tests/test_pallas.py) and exposed via `impl='pallas'`. The page-table
binary search (`paged_page_search`) is scalar log-depth work that stays
plain XLA in every impl — only the bandwidth-bound page reads are kernel
territory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 8  # dst rows per grid step


def _kernel(k, x_ref, slot_ref, w_ref, out_ref, scratch, sems):
    # scratch [2, d, k, 128] double buffer: row i+1's neighbor-row DMAs
    # are in flight while row i reduces on the MXU. Statically unrolled
    # (TILE, d, k are compile-time), so buffer indices are constants.
    #
    # Wide features (f > 128) ride the SAME one-lane-tile DMA shape that
    # Mosaic accepts at f <= 128: the caller reshapes the table to
    # [n_src*k, 128] (k column chunks per logical row) and each neighbor
    # issues k row copies from slot*k+c — a two-level gather instead of
    # an unaligned (1, k*128) HBM slice, which Mosaic rejects.
    d = scratch.shape[1]

    def copies(i, buf):
        for j in range(d):
            for c in range(k):
                yield pltpu.make_async_copy(
                    x_ref.at[slot_ref[i, j] * k + c],
                    scratch.at[buf, j, c],
                    sems.at[buf, j, c],
                )

    start = lambda i, buf: [cp.start() for cp in copies(i, buf)]
    wait = lambda i, buf: [cp.wait() for cp in copies(i, buf)]

    start(0, 0)
    for i in range(TILE):
        if i + 1 < TILE:
            start(i + 1, (i + 1) % 2)
        wait(i, i % 2)
        out_ref[i, :] = jnp.dot(
            w_ref[i, :].reshape(1, d),
            scratch[i % 2].reshape(d, k * 128),
            preferred_element_type=jnp.float32,
        )[0]


def _pallas_forward(x, slots, w, interpret: bool):
    n_dst, d = slots.shape
    f = x.shape[1]
    # feature width padded to the 128-lane register width — narrower or
    # non-multiple rows fail Mosaic's tiling, and the DMA copies stay
    # row-aligned
    padf = (-f) % 128
    if padf:
        x = jnp.pad(x, ((0, 0), (0, padf)))
    pad = (-n_dst) % TILE
    if pad:
        slots = jnp.pad(slots, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n = slots.shape[0]
    fp = f + padf
    k = fp // 128
    x = x.astype(jnp.float32).reshape(-1, 128)  # [n_src*k, 128]
    out = pl.pallas_call(
        functools.partial(_kernel, k),
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # x stays in HBM
            pl.BlockSpec((TILE, d), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (TILE, fp), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, fp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, d, k, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, d, k)),
        ],
        interpret=interpret,
    )(x, slots, w.astype(jnp.float32))
    return out[:n_dst, :f]


def _reference_forward(x, slots, w):
    gathered = jnp.take(x, slots, axis=0)  # [N, D, F]
    return jnp.einsum("nd,ndf->nf", w, gathered)


# Where the DMA kernel beats XLA's gather+einsum, measured on v5e
# (ops/PALLAS_BENCH.md has the full grid): auto picks the fused kernel in
# the region validated end-to-end (+14% GraphSAGE at f=128 in r2;
# re-confirmed r5: 5.12M vs 3.25M edges/s back to back). The 128 cap is a
# MEASURED boundary, not caution: the r5 on-chip wide-F A/B (dims 256,
# artifacts/widef_{off,pallas}.json) has XLA at 8.18M vs pallas 5.18M
# edges/s — at f > 128 the chunked gather's k-fold DMA descriptors lose
# to XLA's single-stream fused gather+einsum. f > 128 stays fully
# supported via impl='pallas' for chips where that tradeoff shifts.
_PALLAS_AUTO_MAX_F = 128
_PALLAS_MIN_DST = 4096


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def gather_weighted_sum(x, slots, w, impl: str = "auto"):
    """out[i] = Σ_j w[i,j] · x[slots[i,j]].

    impl: 'pallas' | 'interpret' | 'xla' | 'auto'. 'auto' picks the DMA
    kernel only where it measured faster than XLA on TPU (see
    ops/PALLAS_BENCH.md); an explicit 'pallas' never silently falls back.
    """
    return _forward(x, slots, w, impl)


def _forward(x, slots, w, impl):
    f = x.shape[1]
    if impl == "auto":
        on_tpu = jax.devices()[0].platform == "tpu"
        impl = (
            "pallas"
            if on_tpu
            and 64 < f <= _PALLAS_AUTO_MAX_F
            and slots.shape[0] >= _PALLAS_MIN_DST
            else "xla"
        )
    if impl == "xla":
        return _reference_forward(x, slots, w)
    return _pallas_forward(x, slots, w, interpret=(impl == "interpret"))


def _fwd(x, slots, w, impl):
    return _forward(x, slots, w, impl), (x, slots, w)


def _bwd(impl, res, g):
    x, slots, w = res
    # dL/dx: scatter-add of w·g into the gathered rows. Accumulate in f32
    # (w is f32, and bf16 scatter-add both loses precision and is a dtype
    # mismatch JAX will reject), then cast the cotangent back to x.dtype.
    contrib = (
        w[:, :, None].astype(jnp.float32) * g[:, None, :].astype(jnp.float32)
    )  # [N, D, F]
    dx = (
        jnp.zeros(x.shape, jnp.float32)
        .at[slots.reshape(-1)]
        .add(contrib.reshape(-1, x.shape[1]))
        .astype(x.dtype)
    )
    # dL/dw: per-slot inner product with g
    gathered = jnp.take(x, slots, axis=0)
    dw = jnp.einsum(
        "nf,ndf->nd",
        g.astype(jnp.float32),
        gathered.astype(jnp.float32),
    ).astype(w.dtype)
    return dx, None, dw


gather_weighted_sum.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Paged ragged-indirection kernels (device-resident sampling lane)
# ---------------------------------------------------------------------------

# the flat page buffers are viewed [M, PAGE_LANES] so every DMA is a
# one-row, lane-aligned copy — the exact shape Mosaic already accepts in
# the gather_weighted_sum chunked path above. Logical page_size must
# divide PAGE_LANES, so one page never straddles a lane row.
PAGE_LANES = 128


def _as_lane_rows(flat):
    """Flat 4-byte-dtype buffer → [M, PAGE_LANES] lane-row view (padded)."""
    flat = flat.reshape(-1)
    pad = (-flat.shape[0]) % PAGE_LANES
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, PAGE_LANES)


def _paged_gather_kernel(k, table_ref, fidx_ref, out_ref, scratch, sems):
    # per (row i, draw j): DMA the lane row holding flat element
    # fidx[i, j] into double-buffered scratch, then select its lane with
    # an iota compare-sum (vector select — no dynamic lane extract).
    def copies(i, buf):
        for j in range(k):
            yield pltpu.make_async_copy(
                table_ref.at[fidx_ref[i, j] // PAGE_LANES],
                scratch.at[buf, j],
                sems.at[buf, j],
            )

    start = lambda i, buf: [cp.start() for cp in copies(i, buf)]  # noqa: E731
    wait = lambda i, buf: [cp.wait() for cp in copies(i, buf)]  # noqa: E731

    start(0, 0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, PAGE_LANES), 1)
    for i in range(TILE):
        if i + 1 < TILE:
            start(i + 1, (i + 1) % 2)
        wait(i, i % 2)
        vals = []
        for j in range(k):
            lane = fidx_ref[i, j] % PAGE_LANES
            row = scratch[i % 2, j].reshape(1, PAGE_LANES)
            vals.append(jnp.sum(jnp.where(lanes == lane, row, 0)))
        out_ref[i, :] = jnp.stack(vals)


def _paged_gather_pallas(table2d, fidx, interpret: bool):
    n, k = fidx.shape
    pad = (-n) % TILE
    if pad:
        fidx = jnp.pad(fidx, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_paged_gather_kernel, k),
        grid=(fidx.shape[0] // TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # pages stay in HBM
            pl.BlockSpec(
                (TILE, k), lambda i: (i, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE, k), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((fidx.shape[0], k), table2d.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, k, PAGE_LANES), table2d.dtype),
            pltpu.SemaphoreType.DMA((2, k)),
        ],
        interpret=interpret,
    )(table2d, fidx.astype(jnp.int32))
    return out[:n]


def _paged_impl(impl: str) -> str:
    # no on-chip profiling exists yet for the paged kernels, so `auto`
    # routes everywhere to the jitted jnp reference (same stance as the
    # measured _PALLAS_AUTO_MAX_F boundary above: auto only picks pallas
    # where a win is measured). 'pallas'/'interpret' stay explicit.
    if impl == "auto":
        return "xla"
    if impl not in ("xla", "pallas", "interpret"):
        raise ValueError(f"unknown impl {impl!r}")
    return impl


def paged_gather(table2d, fidx, impl: str = "auto"):
    """out[i, j] = flat(table2d)[fidx[i, j]] — ragged gather through the
    paged indirection. `table2d` is a [M, 128] lane-row view of a flat
    page buffer (`_as_lane_rows`); `fidx` int32 [W, k] flat element
    indices (page*page_size + slot). 4-byte dtypes only."""
    impl = _paged_impl(impl)
    if impl == "xla":
        flat = table2d.reshape(-1)
        return flat[fidx]
    return _paged_gather_pallas(table2d, fidx, interpret=(impl == "interpret"))


def pack_bf16_words(flat):
    """f32 1-D buffer → uint32 words, two bf16 values per word (low half
    = even index, high half = odd). This keeps quantized feature pages in
    the SAME 4-byte lane-row shape the validated DMA path uses — bf16's
    native (16, 128) min tile never enters the kernel; the u32 word is
    split after the lane select. bf16 here is truncation-free f32
    prefixes, so unpack (<< 16 + bitcast) is exact bf16 → f32."""
    flat = jnp.asarray(flat).reshape(-1)
    u16 = jax.lax.bitcast_convert_type(
        flat.astype(jnp.bfloat16), jnp.uint16
    ).astype(jnp.uint32)
    if u16.shape[0] % 2:
        u16 = jnp.pad(u16, (0, 1))
    pair = u16.reshape(-1, 2)
    return pair[:, 0] | (pair[:, 1] << 16)


def _unpack_bf16_word(word, odd):
    # select the half, re-widen to f32 by shifting into the high bits —
    # bf16 is a truncated f32, so this is the exact inverse of the pack
    half = jnp.where(odd, word >> 16, word) & 0xFFFF
    return jax.lax.bitcast_convert_type(
        (half << 16).astype(jnp.uint32), jnp.float32
    )


def _paged_gather_dequant_kernel(k, table_ref, fidx_ref, out_ref, scratch,
                                 sems):
    # same DMA/iota-select shape as _paged_gather_kernel, but fidx is a
    # logical bf16 element index: the holding u32 word sits at fidx // 2,
    # and the selected word is unpacked in-kernel (the RPA playbook:
    # compact pages in HBM, pay decode next to the gather, not on host).
    def copies(i, buf):
        for j in range(k):
            yield pltpu.make_async_copy(
                table_ref.at[(fidx_ref[i, j] // 2) // PAGE_LANES],
                scratch.at[buf, j],
                sems.at[buf, j],
            )

    start = lambda i, buf: [cp.start() for cp in copies(i, buf)]  # noqa: E731
    wait = lambda i, buf: [cp.wait() for cp in copies(i, buf)]  # noqa: E731

    start(0, 0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, PAGE_LANES), 1)
    for i in range(TILE):
        if i + 1 < TILE:
            start(i + 1, (i + 1) % 2)
        wait(i, i % 2)
        vals = []
        for j in range(k):
            lane = (fidx_ref[i, j] // 2) % PAGE_LANES
            row = scratch[i % 2, j].reshape(1, PAGE_LANES)
            word = jnp.sum(jnp.where(lanes == lane, row, 0))
            vals.append(_unpack_bf16_word(word, fidx_ref[i, j] % 2 == 1))
        out_ref[i, :] = jnp.stack(vals)


def _paged_gather_dequant_pallas(table2d, fidx, interpret: bool):
    n, k = fidx.shape
    pad = (-n) % TILE
    if pad:
        fidx = jnp.pad(fidx, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_paged_gather_dequant_kernel, k),
        grid=(fidx.shape[0] // TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # packed pages stay in HBM
            pl.BlockSpec(
                (TILE, k), lambda i: (i, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE, k), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((fidx.shape[0], k), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, k, PAGE_LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, k)),
        ],
        interpret=interpret,
    )(table2d, fidx.astype(jnp.int32))
    return out[:n]


def paged_gather_dequant(table2d, fidx, impl: str = "auto"):
    """out[i, j] = bf16_unpack(flat(table2d))[fidx[i, j]] as f32 — the
    quantized-page twin of `paged_gather`. `table2d` is a [M, 128]
    lane-row view of a `pack_bf16_words` buffer (uint32, two bf16 per
    word); `fidx` indexes LOGICAL bf16 elements. Dequantize happens at
    the gather (in-kernel for 'pallas'), so HBM and DMA bytes are half
    the f32 path. Same impl discipline as paged_gather: 'auto' → the
    jitted jnp reference; the Pallas form is interpret-validated."""
    impl = _paged_impl(impl)
    fidx = fidx.astype(jnp.int32)
    if impl == "xla":
        flat = table2d.reshape(-1)
        word = flat[fidx // 2]
        return _unpack_bf16_word(word, fidx % 2 == 1)
    return _paged_gather_dequant_pallas(
        table2d, fidx, interpret=(impl == "interpret")
    )


def _paged_count_kernel(k, page_size, q_ref, page_ref, r_ref, out_ref,
                        scratch, sems):
    # per (row i, draw j): DMA the lane row holding page page_ref[i, j]
    # (pages are page_size-aligned, page_size | PAGE_LANES, so a page
    # never straddles rows), then count the page's lanes with q <= r.
    def copies(i, buf):
        for j in range(k):
            yield pltpu.make_async_copy(
                q_ref.at[(page_ref[i, j] * page_size) // PAGE_LANES],
                scratch.at[buf, j],
                sems.at[buf, j],
            )

    start = lambda i, buf: [cp.start() for cp in copies(i, buf)]  # noqa: E731
    wait = lambda i, buf: [cp.wait() for cp in copies(i, buf)]  # noqa: E731

    start(0, 0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, PAGE_LANES), 1)
    for i in range(TILE):
        if i + 1 < TILE:
            start(i + 1, (i + 1) % 2)
        wait(i, i % 2)
        vals = []
        for j in range(k):
            lane0 = (page_ref[i, j] * page_size) % PAGE_LANES
            row = scratch[i % 2, j].reshape(1, PAGE_LANES)
            sel = (lanes >= lane0) & (lanes < lane0 + page_size)
            vals.append(
                jnp.sum(jnp.where(sel & (row <= r_ref[i, j]), 1, 0))
            )
        out_ref[i, :] = jnp.stack(vals).astype(jnp.int32)


def _paged_count_pallas(q2d, page, rbits, page_size: int, interpret: bool):
    n, k = page.shape
    pad = (-n) % TILE
    if pad:
        page = jnp.pad(page, ((0, pad), (0, 0)))
        rbits = jnp.pad(rbits, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_paged_count_kernel, k, page_size),
        grid=(page.shape[0] // TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # quantized CDF in HBM
            pl.BlockSpec(
                (TILE, k), lambda i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                (TILE, k), lambda i: (i, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (TILE, k), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((page.shape[0], k), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((2, k, PAGE_LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, k)),
        ],
        interpret=interpret,
    )(q2d, page.astype(jnp.int32), rbits)
    return out[:n]


def paged_cdf_count(q2d, page, rbits, page_size: int, impl: str = "auto"):
    """In-page quantized-CDF inversion: out[i, j] = |{l < page_size :
    flat(q2d)[page[i, j]*page_size + l] <= rbits[i, j]}| — the slot count
    within the already-selected page. Padding lanes hold 0xFFFFFFFF so
    they count only at rbits == MAX (callers clamp by degree)."""
    impl = _paged_impl(impl)
    if impl == "xla":
        flat = q2d.reshape(-1)
        base = page.astype(jnp.int32) * page_size
        lanes = base[..., None] + jnp.arange(page_size, dtype=jnp.int32)
        q = flat[lanes]  # [W, k, page_size]
        return (q <= rbits[..., None]).sum(axis=-1).astype(jnp.int32)
    return _paged_count_pallas(
        q2d, page, rbits, page_size, interpret=(impl == "interpret")
    )


def paged_page_search(bound, pstart, npages, rbits, iters: int):
    """Per-node upper-bound search over the flat page-boundary array:
    returns [W, k] counts of the node's pages whose boundary (last valid
    quantized-CDF value) is <= rbits — i.e. the pages the draw skips
    entirely. Branchless binary search with a static iteration count
    (`iters` >= bit_length(max pages per node) + 1); pure integer math,
    so it is bit-identical across impls by construction and stays plain
    XLA (log-depth scalar work — not kernel territory)."""
    lo = jnp.broadcast_to(pstart[:, None].astype(jnp.int32), rbits.shape)
    hi = lo + jnp.broadcast_to(npages[:, None].astype(jnp.int32), rbits.shape)
    cap = bound.shape[0] - 1
    for _ in range(max(int(iters), 1)):
        active = lo < hi
        mid = (lo + hi) // 2
        le = bound[jnp.minimum(mid, cap)] <= rbits
        lo = jnp.where(active & le, mid + 1, lo)
        hi = jnp.where(active & ~le, mid, hi)
    return lo - pstart[:, None].astype(jnp.int32)


# ---------------------------------------------------------------------------
# Retrieval scoring kernel (embedding top-K serving lane)
# ---------------------------------------------------------------------------

# lane rows streamed per grid step in the score kernel. Unlike the
# gather kernels above, the corpus scan is data-INdependent (every page
# is read exactly once, in order), so BlockSpec grid streaming stages
# HBM -> VMEM and Mosaic's automatic pipelining double-buffers it — no
# manual DMA/semaphore choreography needed.
SCORE_TILE = 8


def _topk_score_kernel(dp, rows_per, x_ref, q_ref, out_ref):
    # one lane-row tile holds SCORE_TILE * rows_per packed dp-vectors
    # (row-major flat layout, dp | PAGE_LANES so no vector straddles a
    # lane row). The d-loop is a STATIC unroll: the same left-to-right
    # f32 (mul, add) chain as the jitted reference, so scores are
    # bit-identical across impls by construction.
    rows = x_ref.shape[0] * rows_per
    x = x_ref[:].reshape(rows, dp)
    acc = jnp.zeros((q_ref.shape[0], rows), jnp.float32)
    for d in range(dp):
        acc = acc + q_ref[:, d][:, None] * x[:, d][None, :]
    out_ref[:] = acc


def _paged_topk_score_pallas(table2d, q, dp, interpret: bool):
    rows_per = PAGE_LANES // dp
    b = q.shape[0]
    pad = (-table2d.shape[0]) % SCORE_TILE
    if pad:
        table2d = jnp.pad(table2d, ((0, pad), (0, 0)))
    mt = table2d.shape[0]
    # query lane-padded to the register width; the kernel only reads the
    # first dp lanes, and padding with zeros keeps the pad inert
    qp = jnp.pad(q, ((0, 0), (0, PAGE_LANES - dp)))
    return pl.pallas_call(
        functools.partial(_topk_score_kernel, dp, rows_per),
        grid=(mt // SCORE_TILE,),
        in_specs=[
            pl.BlockSpec(
                (SCORE_TILE, PAGE_LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (b, PAGE_LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (b, SCORE_TILE * rows_per),
            lambda i: (0, i),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, mt * rows_per), jnp.float32),
        interpret=interpret,
    )(table2d, qp)


def paged_topk_score(table2d, q, nrows: int, dp: int, impl: str = "auto"):
    """scores[b, i] = <flat(table2d)[i*dp : (i+1)*dp], q[b, :dp]> — the
    brute-force retrieval scorer over a paged corpus.

    `table2d` is the [M, 128] lane-row view (`_as_lane_rows`) of a flat
    f32 buffer holding `nrows` packed dp-wide vectors; `q` is [B, dp]
    f32 queries. Returns [B, nrows] f32 scores.

    Bit-reproducibility contract (the retrieval parity oracle leans on
    it): the dot product accumulates STRICTLY left-to-right in f32 —
    acc = f32(acc + x[d] * q[d]) for d = 0..dp-1 — in every impl and in
    the NumPy oracle (retrieval/topk.py), so scores are bit-identical
    across 'xla'/'pallas'/'interpret'/NumPy rather than at the mercy of
    a reduction order XLA is free to pick. The contract additionally
    REQUIRES operands with 12-bit-truncated significands
    (retrieval/corpus.py quantize_sig12): LLVM contracts the mul+add
    into FMA non-uniformly on CPU (no HLO barrier or XLA flag stops
    it), and only exact products — which 12x12-bit significands
    guarantee — make fma(x, q, acc) == f32(x*q) + acc identically.
    Same impl discipline as paged_gather: 'auto' routes to the jitted
    reference until a measured on-chip win; the Pallas form ('pallas',
    dp | 128 only) is interpret-validated in tests/test_pallas.py.
    """
    impl = _paged_impl(impl)
    q = q.astype(jnp.float32)
    if impl == "xla":
        flat = table2d.reshape(-1)[: nrows * dp]
        x = flat.astype(jnp.float32).reshape(nrows, dp)

        def body(d, acc):
            xcol = jax.lax.dynamic_index_in_dim(x, d, 1, keepdims=False)
            qcol = jax.lax.dynamic_index_in_dim(q, d, 1, keepdims=False)
            return acc + qcol[:, None] * xcol[None, :]

        acc = jnp.zeros((q.shape[0], nrows), jnp.float32)
        return jax.lax.fori_loop(0, dp, body, acc)
    if dp < 1 or PAGE_LANES % dp:
        raise ValueError(
            f"paged_topk_score pallas impl needs dp | {PAGE_LANES}, got {dp}"
        )
    out = _paged_topk_score_pallas(
        table2d.astype(jnp.float32), q, dp, interpret=(impl == "interpret")
    )
    return out[:, :nrows]
