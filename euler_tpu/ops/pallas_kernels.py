"""Pallas TPU kernels for the message-passing hot path.

`gather_weighted_sum(x, slots, w)` fuses the neighbor gather with the
weighted segment reduction: out[i] = Σ_j w[i, j] · x[slots[i, j]].

Every euler_tpu dataflow emits *grid-structured* blocks (each dst row owns a
fixed strip of D neighbor slots), so the aggregation is this one primitive —
it subsumes SAGE-mean (w = mask/deg), GCN (w = norm products), and weighted
sums, without materializing the [E, F] message tensor in HBM. The kernel
keeps the feature table in HBM, DMA-gathers each row's D neighbor vectors
into VMEM scratch, and reduces them with a (1×D)·(D×F) matmul on the MXU.

Backward is pure JAX (scatter-add of w·g, and g·x for the weights) via
custom_vjp — gradient layout matches mp_ops (reference mp_ops.py:39-62).

CPU/interpret fallback makes the same entry point usable in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 8  # dst rows per grid step


def _kernel(x_ref, slot_ref, w_ref, out_ref, scratch, sems):
    d = scratch.shape[0]

    def row(i, _):
        for j in range(d):
            pltpu.make_async_copy(
                x_ref.at[slot_ref[i, j]], scratch.at[j], sems.at[j]
            ).start()
        for j in range(d):
            pltpu.make_async_copy(
                x_ref.at[slot_ref[i, j]], scratch.at[j], sems.at[j]
            ).wait()
        out_ref[i, :] = jnp.dot(
            w_ref[i, :].reshape(1, d),
            scratch[:],
            preferred_element_type=jnp.float32,
        )[0]
        return 0

    jax.lax.fori_loop(0, TILE, row, 0)


def _pallas_forward(x, slots, w, interpret: bool):
    n_dst, d = slots.shape
    f = x.shape[1]
    pad = (-n_dst) % TILE
    if pad:
        slots = jnp.pad(slots, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    n = slots.shape[0]
    out = pl.pallas_call(
        _kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # x stays in HBM
            pl.BlockSpec((TILE, d), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (TILE, f), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, f), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((d, f), jnp.float32),
            pltpu.SemaphoreType.DMA((d,)),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32), slots, w.astype(jnp.float32))
    return out[:n_dst]


def _reference_forward(x, slots, w):
    gathered = jnp.take(x, slots, axis=0)  # [N, D, F]
    return jnp.einsum("nd,ndf->nf", w, gathered)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def gather_weighted_sum(x, slots, w, impl: str = "auto"):
    """out[i] = Σ_j w[i,j] · x[slots[i,j]].

    impl: 'pallas' | 'interpret' | 'xla' | 'auto' (pallas on TPU else xla).
    """
    return _forward(x, slots, w, impl)


def _forward(x, slots, w, impl):
    if impl == "auto":
        impl = "pallas" if jax.devices()[0].platform == "tpu" else "xla"
    if impl == "xla":
        return _reference_forward(x, slots, w)
    return _pallas_forward(x, slots, w, interpret=(impl == "interpret"))


def _fwd(x, slots, w, impl):
    return _forward(x, slots, w, impl), (x, slots, w)


def _bwd(impl, res, g):
    x, slots, w = res
    # dL/dx: scatter-add of w·g into the gathered rows
    contrib = w[:, :, None] * g[:, None, :]  # [N, D, F]
    dx = jnp.zeros_like(x).at[slots.reshape(-1)].add(
        contrib.reshape(-1, x.shape[1])
    )
    # dL/dw: per-slot inner product with g
    gathered = jnp.take(x, slots, axis=0)
    dw = jnp.einsum("nf,ndf->nd", g, gathered)
    return dx, None, dw


gather_weighted_sum.defvjp(_fwd, _bwd)
