"""KG-embedding sweeps as a whole-graph workload (ISSUE 12).

A sweep drives ``models/kg.py`` TransE/DistMult-family training through
full-graph negative-sampling epochs, with the three disciplines the
analytics lane guarantees everywhere else:

  epoch pinning    the triple list, entity universe and evaluation set
      are extracted ONCE from a ``WholeGraphEngine`` (which captures the
      shard stores at construction), so every config in the sweep trains
      and evaluates against exactly one published ``graph_epoch`` even
      while writers stream mutations.
  determinism      triples are collected in sorted (h, r, t) order;
      batches cycle a seeded permutation of the full pinned triple list
      (reshuffled per epoch) with negatives drawn from the pinned entity
      list — two runs of the same sweep produce identical leaderboards.
  durability       each config commits its final params/opt state
      through the PR-10 retained checkpoint store (atomic tmp → fsync →
      COMMIT → rename, keep-N), with the epoch pin and the evaluation
      metrics in the checkpoint meta; re-running the sweep with
      ``resume=True`` skips configs whose committed checkpoint already
      matches the pinned epoch (a shard death mid-sweep surfaces as the
      usual typed RpcError, and the restart pays only for the configs
      that had not committed — OPERATIONS.md).

Evaluation uses the filtered ranking metrics (``kg_ranking_metrics``)
with the pinned triple list as the filter set.
"""

from __future__ import annotations

import os

import numpy as np

from euler_tpu.analytics.primitives import WholeGraphEngine
from euler_tpu.training.checkpoint import CheckpointStore

DEFAULT_CONFIGS = (
    {"variant": "transe", "dim": 16, "learning_rate": 0.05},
    {"variant": "distmult", "dim": 16, "learning_rate": 0.05},
)


def collect_triples(graph, edge_types=None, engine=None):
    """Pinned-epoch triple extraction: every edge as (h=src id,
    r=type, t=dst id), int64 [E, 3] sorted by (h, r, t) — the
    deterministic full-graph training set AND the filter set for the
    filtered ranking metrics. Returns (triples, entity_ids, engine)."""
    if engine is None:
        engine = WholeGraphEngine(graph, edge_types=edge_types)
    h = engine.edge_src_id.astype(np.int64)
    t = engine.node_ids[engine.edge_dst].astype(np.int64)
    r = engine.edge_tt.astype(np.int64)
    triples = np.stack([h, r, t], axis=1)
    order = np.lexsort((triples[:, 2], triples[:, 1], triples[:, 0]))
    triples = triples[order]
    entity_ids = np.sort(engine.node_ids.astype(np.int64))
    return triples, entity_ids, engine


def pinned_kg_batches(
    triples: np.ndarray,
    entity_ids: np.ndarray,
    batch_size: int,
    num_negs: int = 4,
    rng=None,
    seed: int = 0,
):
    """Batch source over the PINNED triple list: cycles a seeded
    permutation of all triples (reshuffled each epoch — full-graph
    negative-sampling epochs, not i.i.d. edge draws) with corrupted
    heads/tails drawn uniformly from the pinned entity list."""
    rng = rng if rng is not None else np.random.default_rng(seed)
    triples = np.asarray(triples, np.int64)
    entity_ids = np.asarray(entity_ids, np.int64)
    state = {"perm": rng.permutation(len(triples)), "pos": 0}

    def to32(x):
        return np.asarray(x, np.int64).astype(np.int32)

    def fn():
        take = []
        need = batch_size
        while need > 0:
            perm, pos = state["perm"], state["pos"]
            got = perm[pos:pos + need]
            take.append(got)
            need -= len(got)
            state["pos"] = pos + len(got)
            if state["pos"] >= len(perm):  # epoch boundary: reshuffle
                state["perm"] = rng.permutation(len(triples))
                state["pos"] = 0
        e = triples[np.concatenate(take)]
        negs = entity_ids[
            rng.integers(0, len(entity_ids), batch_size * num_negs * 2)
        ].reshape(2, batch_size, num_negs)
        return (
            {
                "h": to32(e[:, 0]),
                "r": to32(e[:, 1]),
                "t": to32(e[:, 2]),
                "neg_h": to32(negs[0]),
                "neg_t": to32(negs[1]),
            },
        )

    return fn


def _config_name(cfg: dict) -> str:
    lr = cfg.get("learning_rate", 0.05)
    return f"{cfg.get('variant', 'transe')}_d{cfg.get('dim', 16)}_lr{lr}"


def run_kg_sweep(
    graph,
    out_dir: str,
    configs=None,
    steps: int = 40,
    batch_size: int = 32,
    num_negs: int = 4,
    seed: int = 0,
    edge_types=None,
    eval_triples: int = 128,
    keep: int = 3,
    resume: bool = True,
) -> dict:
    """Sweep KG-embedding configs over the pinned full graph; returns
    {"epoch_pin", "num_triples", "num_entities", "leaderboard"} with the
    leaderboard sorted by filtered MRR (desc, ties by config name)."""
    import jax

    from euler_tpu.estimator import Estimator, EstimatorConfig
    from euler_tpu.models.kg import TransX, kg_ranking_metrics

    configs = [dict(c) for c in (configs or DEFAULT_CONFIGS)]
    triples, entity_ids, engine = collect_triples(
        graph, edge_types=edge_types
    )
    epoch_pin = list(engine.epoch_pin)
    num_entities = int(entity_ids.max(initial=0))
    num_relations = max(
        int(engine.edge_tt.max(initial=0)) + 1,
        int(getattr(graph.meta, "num_edge_types", 1)),
    )
    eval_set = triples[:min(int(eval_triples), len(triples))]
    leaderboard = []
    for i, cfg in enumerate(configs):
        name = _config_name(cfg)
        mdir = os.path.join(out_dir, name)
        store = CheckpointStore(mdir, keep=keep)
        if resume:
            step = store.latest_step()
            if step is not None:
                meta = store.load(step)["meta"]
                sweep_meta = meta.get("sweep") or {}
                if (
                    meta.get("graph_epoch") == epoch_pin
                    and sweep_meta.get("metrics")
                ):
                    leaderboard.append({
                        "name": name,
                        "config": cfg,
                        "metrics": sweep_meta["metrics"],
                        "final_loss": sweep_meta.get("final_loss"),
                        "checkpoint": store._path(step),
                        "resumed": True,
                    })
                    continue
        rng = np.random.default_rng(seed + i)
        model = TransX(
            num_entities=num_entities,
            num_relations=num_relations,
            dim=int(cfg.get("dim", 16)),
            rel_dim=int(cfg.get("rel_dim", 0)),
            variant=cfg.get("variant", "transe"),
        )
        est_cfg = EstimatorConfig(
            model_dir=mdir,
            total_steps=int(steps),
            learning_rate=float(cfg.get("learning_rate", 0.05)),
            log_steps=10**9,
            seed=seed,
        )
        est = Estimator(
            model,
            pinned_kg_batches(
                triples, entity_ids, batch_size,
                num_negs=num_negs, rng=rng,
            ),
            est_cfg,
        )
        hist = est.train(save=False)
        metrics = kg_ranking_metrics(
            model, est.params, eval_set, num_entities,
            filter_triples=triples,
        )
        p_leaves = [
            np.asarray(v) for v in jax.tree_util.tree_leaves(est.params)
        ]
        o_leaves = [
            np.asarray(v) for v in jax.tree_util.tree_leaves(est.opt_state)
        ]
        path = store.save_leaves(
            int(steps), p_leaves, o_leaves,
            extra_meta={
                "graph_epoch": epoch_pin,
                "sweep": {
                    "name": name,
                    "config": cfg,
                    "seed": int(seed),
                    "metrics": metrics,
                    "final_loss": float(np.asarray(hist)[-1]),
                },
            },
        )
        leaderboard.append({
            "name": name,
            "config": cfg,
            "metrics": metrics,
            "final_loss": float(np.asarray(hist)[-1]),
            "checkpoint": path,
            "resumed": False,
        })
    leaderboard.sort(key=lambda e: (-e["metrics"]["mrr"], e["name"]))
    return {
        "epoch_pin": epoch_pin,
        "num_triples": int(len(triples)),
        "num_entities": num_entities,
        "leaderboard": leaderboard,
    }
