"""Whole-graph offline analytics (ISSUE 12): the third workload class
next to training and serving — bulk-synchronous PageRank / label
propagation / connected components over the sharded CSR partitions,
plus KG-embedding sweeps with retained checkpoints. Every run pins one
published graph epoch and is bit-deterministic across shard counts and
local/remote execution."""

from euler_tpu.analytics.algorithms import (  # noqa: F401
    AnalyticsResult,
    connected_components,
    label_propagation,
    pagerank,
    rerun_incremental,
)
from euler_tpu.analytics.primitives import (  # noqa: F401
    ShardedFrontier,
    WholeGraphEngine,
    broadcast,
    map_shards,
    reduce_messages,
    reduce_scatter_frontier,
)
from euler_tpu.analytics.sweeps import run_kg_sweep  # noqa: F401
