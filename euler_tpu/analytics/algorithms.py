"""Whole-graph iterative algorithms over the BSP primitives.

PageRank (weighted, damped, tolerance stop), label propagation
(weighted majority vote) and connected components (iterative min-label)
— each bit-deterministic across shard counts AND across local/remote
execution, because every reduction runs through the canonical order in
``primitives.reduce_messages`` (sorted segment reductions, never
set-iteration).

The PageRank variant deliberately skips dangling-mass redistribution
(r = (1-d)/N + d·Σ w_norm·r[src]): redistribution couples every row to
every dangling row globally, which would make the incremental dirty set
the whole graph after one step. Without it each row depends only on its
in-neighbors, so incremental recompute stays local to the mutation.

Incremental recompute (``rerun_incremental``) is MEMOIZED REPLAY, not
warm-starting: the from-scratch run records its per-iteration
trajectory; the rerun replays the same iteration schedule, recomputing
only rows whose inputs could differ (the publish result's mutated-row
set, propagated one out-edge hop per iteration) and copying every other
row from the recorded trajectory. The rerun therefore converges to the
SAME fixed point with the SAME iteration count and bit pattern as a
from-scratch run at the new epoch — pinned by tests/test_analytics.py —
while ``stats["rows_recomputed"]`` proves it touched only the mutated
region.

Long runs can checkpoint the frontier through the PR-10 retained
checkpoint store (``checkpoint_dir``/``checkpoint_every``): a shard
death mid-sweep surfaces as the usual typed RpcError, and the rerun
with ``resume=True`` continues from the last committed frontier —
bit-identical to an uninterrupted run, because iteration math never
depends on wall clock or history.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from euler_tpu.analytics.primitives import (
    WholeGraphEngine,
    _ragged_take,
)
from euler_tpu.training.checkpoint import CheckpointStore


@dataclass
class AnalyticsResult:
    """One pinned-epoch analytics run: values are f64 per global row
    (shard-major); ``by_id()`` is the shard-count-independent view."""

    algo: str
    values: np.ndarray
    node_ids: np.ndarray
    offsets: np.ndarray
    epoch_pin: tuple
    iterations: int
    converged: bool
    trajectory: list | None
    stats: dict = field(default_factory=dict)
    params: dict = field(default_factory=dict)

    def by_id(self):
        order = np.argsort(self.node_ids, kind="stable")
        return self.node_ids[order], np.asarray(self.values)[order]

    def labels_by_id(self):
        ids, vals = self.by_id()
        return ids, vals.astype(np.int64)


def _bits(v: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(v, np.float64)).view(np.uint64)


def _out_neighbors(engine, rows: np.ndarray) -> np.ndarray:
    """Global rows reachable over one out-edge from `rows` — the dirty
    set's per-iteration propagation front."""
    if len(rows) == 0:
        return np.empty(0, np.int64)
    starts = engine._out_indptr[rows]
    lens = engine._out_indptr[rows + 1] - starts
    return np.unique(engine._out_dst[_ragged_take(starts, lens)])


def _id_ranks(engine) -> np.ndarray:
    """Initial label per row: the node id's rank in the global sorted
    id order — dense, and identical per NODE for every shard count."""
    rank = np.empty(engine.num_rows, np.int64)
    rank[np.argsort(engine.node_ids, kind="stable")] = np.arange(
        engine.num_rows, dtype=np.int64
    )
    return rank.astype(np.float64)


def _local_rows(engine, p: int, dirty: np.ndarray | None):
    if dirty is None:
        return None
    lo, hi = engine.offsets[p], engine.offsets[p + 1]
    return dirty[(dirty >= lo) & (dirty < hi)] - lo


def _norm_weights(engine, p: int) -> np.ndarray:
    part = engine.parts[p]
    if "wn" not in part:
        denom = engine.out_w[part["src"]]
        part["wn"] = np.divide(
            part["w"], denom,
            out=np.zeros_like(part["w"]), where=denom > 0,
        )
    return part["wn"]


# ---------------------------------------------------------------------------
# per-iteration kernels: (engine, cur, dirty_global|None, base|None) → new
# ---------------------------------------------------------------------------


def _step_pagerank(engine, cur, dirty, base, damping):
    n = engine.num_rows
    teleport = (1.0 - damping) / n
    if base is None:
        new = np.full(n, teleport, np.float64)
    else:
        new = base
        new[dirty] = teleport
    for p in range(engine.num_shards):
        local = _local_rows(engine, p, dirty)
        if local is not None and len(local) == 0:
            continue
        rows, eidx = engine.gather_edges(p, local)
        vals = engine.contrib(p, eidx, cur, _norm_weights(engine, p))
        u, v, _ = engine.exchange(p, rows, eidx, vals, "sum")
        new[u + engine.offsets[p]] += damping * v
    return new


def _step_label_prop(engine, cur, dirty, base):
    if base is None:
        new = cur.copy()
    else:
        new = base
        new[dirty] = cur[dirty]  # rows with no votes keep their label
    for p in range(engine.num_shards):
        local = _local_rows(engine, p, dirty)
        if local is not None and len(local) == 0:
            continue
        rows, eidx = engine.gather_edges(p, local)
        part = engine.parts[p]
        keys = cur[part["src"][eidx]].astype(np.int64)
        u, _, k = engine.exchange(p, rows, keys, part["w"][eidx], "vote")
        new[u + engine.offsets[p]] = k.astype(np.float64)
    return new


def _step_components(engine, cur, dirty, base):
    if base is None:
        new = cur.copy()
    else:
        new = base
        new[dirty] = cur[dirty]
    for p in range(engine.num_shards):
        local = _local_rows(engine, p, dirty)
        if local is not None and len(local) == 0:
            continue
        rows, eidx = engine.gather_edges(p, local)
        vals = cur[engine.parts[p]["src"][eidx]]
        u, v, _ = engine.exchange(p, rows, eidx, vals, "min")
        g = u + engine.offsets[p]
        new[g] = np.minimum(new[g], v)
    return new


# ---------------------------------------------------------------------------
# the shared BSP loop: from-scratch AND memoized incremental replay
# ---------------------------------------------------------------------------


def _loop(
    engine,
    algo: str,
    params: dict,
    init_vec: np.ndarray,
    step_fn,
    stop_fn,
    max_iters: int,
    prev: AnalyticsResult | None = None,
    struct_dirty: np.ndarray | None = None,
    keep_trajectory: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    resume: bool = False,
) -> AnalyticsResult:
    n = engine.num_rows
    cur = np.asarray(init_vec, np.float64)
    memo = None
    if prev is not None and struct_dirty is not None:
        memo = prev.trajectory
        if (
            memo is None
            or len(memo[0]) != n
            or not np.array_equal(_bits(memo[0]), _bits(cur))
        ):
            memo = None  # row space or init moved → full recompute
    if struct_dirty is not None:
        struct_dirty = np.unique(np.asarray(struct_dirty, np.int64))
        struct_dirty = struct_dirty[(struct_dirty >= 0) & (struct_dirty < n)]
        if memo is None:
            struct_dirty = None
    it = 0
    ckpt = CheckpointStore(checkpoint_dir) if checkpoint_dir else None
    if ckpt is not None and resume:
        step = ckpt.latest_step()
        if step is not None:
            snap = ckpt.load(step)
            meta = snap["meta"]
            if (
                meta.get("algo") == algo
                and tuple(meta.get("epoch_pin", ())) == tuple(engine.epoch_pin)
                and len(snap["params"][0]) == n
            ):
                cur = np.asarray(snap["params"][0], np.float64)
                it = int(snap["step"])
                memo = None  # a resumed run replays nothing
                struct_dirty = None
    traj = [cur.copy()]
    # rows whose value differs bitwise from the memoized trajectory at
    # the current iteration; None = unknown/all (forces full compute)
    changed = np.empty(0, np.int64) if memo is not None else None
    rows_recomputed = 0
    converged = False
    while it < max_iters:
        it += 1
        if (
            struct_dirty is not None
            and changed is not None
            and memo is not None
            and it < len(memo)
        ):
            dirty = np.union1d(struct_dirty, _out_neighbors(engine, changed))
            new = step_fn(engine, cur, dirty, memo[it].copy())
            changed = dirty[
                _bits(new[dirty]) != _bits(np.asarray(memo[it])[dirty])
            ]
            rows_recomputed += len(dirty)
        else:
            new = step_fn(engine, cur, None, None)
            changed = None
            rows_recomputed += n
        traj.append(new)
        if ckpt is not None and checkpoint_every and it % checkpoint_every == 0:
            ckpt.save_leaves(
                it, [new], [],
                extra_meta={
                    "algo": algo,
                    "epoch_pin": list(engine.epoch_pin),
                    "params": {
                        k: v for k, v in params.items()
                        if isinstance(v, (int, float, str, bool))
                    },
                },
            )
        stop = stop_fn(cur, new)
        cur = new
        if stop:
            converged = True
            break
    stats = dict(engine.stats)
    stats["rows_recomputed"] = rows_recomputed
    stats["num_rows"] = n
    stats["num_edges"] = engine.num_edges
    stats["boundary_edges"] = engine.boundary_edges
    return AnalyticsResult(
        algo=algo,
        values=cur,
        node_ids=engine.node_ids,
        offsets=engine.offsets,
        epoch_pin=tuple(engine.epoch_pin),
        iterations=it,
        converged=converged,
        trajectory=traj if keep_trajectory else None,
        stats=stats,
        params=dict(params),
    )


def _make_engine(graph, params: dict) -> WholeGraphEngine:
    return WholeGraphEngine(
        graph,
        edge_types=params.get("edge_types"),
        device=bool(params.get("device", False)),
        exchange=params.get("exchange", "auto"),
        symmetric=bool(params.get("symmetric", False)),
    )


# ---------------------------------------------------------------------------
# public algorithms
# ---------------------------------------------------------------------------


def pagerank(
    graph,
    damping: float = 0.85,
    tol: float = 1e-10,
    max_iters: int = 100,
    edge_types=None,
    device: bool = False,
    exchange: str = "auto",
    engine: WholeGraphEngine | None = None,
    keep_trajectory: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    _prev: AnalyticsResult | None = None,
    _struct_dirty=None,
) -> AnalyticsResult:
    """Weighted damped PageRank with a tolerance stop (max |Δ| ≤ tol
    over the full vector). No dangling-mass redistribution — see the
    module docstring for why that keeps incremental recompute local."""
    params = {
        "damping": float(damping), "tol": float(tol),
        "max_iters": int(max_iters), "edge_types": edge_types,
        "device": bool(device), "exchange": exchange, "symmetric": False,
    }
    if engine is None:
        engine = _make_engine(graph, params)
    n = engine.num_rows
    init = np.full(n, 1.0 / n if n else 0.0, np.float64)
    return _loop(
        engine, "pagerank", params, init,
        lambda e, cur, dirty, base: _step_pagerank(
            e, cur, dirty, base, params["damping"]
        ),
        lambda cur, new: bool(
            np.max(np.abs(new - cur), initial=0.0) <= params["tol"]
        ),
        params["max_iters"],
        prev=_prev, struct_dirty=_struct_dirty,
        keep_trajectory=keep_trajectory,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        resume=resume,
    )


def label_propagation(
    graph,
    max_iters: int = 30,
    edge_types=None,
    device: bool = False,
    exchange: str = "auto",
    engine: WholeGraphEngine | None = None,
    keep_trajectory: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    _prev: AnalyticsResult | None = None,
    _struct_dirty=None,
) -> AnalyticsResult:
    """Synchronous weighted label propagation: each row adopts the
    in-neighbor label with the highest total edge weight (ties to the
    smallest label); rows with no in-edges keep their own. Labels start
    as global id-ranks, so they are node-identity stable."""
    params = {
        "max_iters": int(max_iters), "edge_types": edge_types,
        "device": bool(device), "exchange": exchange, "symmetric": False,
    }
    if engine is None:
        engine = _make_engine(graph, params)
    init = _id_ranks(engine)
    return _loop(
        engine, "lp", params, init, _step_label_prop,
        lambda cur, new: bool(np.array_equal(_bits(cur), _bits(new))),
        params["max_iters"],
        prev=_prev, struct_dirty=_struct_dirty,
        keep_trajectory=keep_trajectory,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        resume=resume,
    )


def connected_components(
    graph,
    max_iters: int = 200,
    edge_types=None,
    device: bool = False,
    exchange: str = "auto",
    engine: WholeGraphEngine | None = None,
    keep_trajectory: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    _prev: AnalyticsResult | None = None,
    _struct_dirty=None,
) -> AnalyticsResult:
    """Connected components on the undirected view: iterative min-label
    until fixpoint. Component label = smallest member id-rank."""
    params = {
        "max_iters": int(max_iters), "edge_types": edge_types,
        "device": bool(device), "exchange": exchange, "symmetric": True,
    }
    if engine is None:
        engine = _make_engine(graph, params)
    init = _id_ranks(engine)
    return _loop(
        engine, "cc", params, init, _step_components,
        lambda cur, new: bool(np.array_equal(_bits(cur), _bits(new))),
        params["max_iters"],
        prev=_prev, struct_dirty=_struct_dirty,
        keep_trajectory=keep_trajectory,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        resume=resume,
    )


_ALGOS = {
    "pagerank": pagerank,
    "lp": label_propagation,
    "cc": connected_components,
}


def rerun_incremental(
    graph,
    prev: AnalyticsResult,
    publish: dict | None = None,
    mutated_rows=None,
    engine: WholeGraphEngine | None = None,
    keep_trajectory: bool = True,
) -> AnalyticsResult:
    """Recompute ``prev`` against the CURRENT epoch, touching only rows
    the mutation could have reached.

    ``mutated_rows`` (or ``publish["rows"]`` from ``GraphWriter.publish``)
    seeds the dirty set; each iteration the set advances one out-edge
    hop, every clean row is copied from ``prev.trajectory``, and the
    replayed schedule converges to bit-exactly the from-scratch result
    at the new epoch. Degrades to a full recompute when the mutated-row
    set is unknown (publish rows=None), the node count moved, or the
    previous run kept no trajectory. Passing the previous run's
    ``engine`` also reuses its cached adjacency, refetching only the
    mutated rows (``stats["rows_refetched"]``).
    """
    if prev.algo not in _ALGOS:
        raise ValueError(f"unknown analytics algo {prev.algo!r}")
    rows = mutated_rows
    if rows is None and publish is not None:
        rows = publish.get("rows")
    if rows is not None:
        rows = np.asarray(rows, np.int64)
    if (
        publish is not None
        and publish.get("num_nodes") is not None
        and int(publish["num_nodes"]) != len(prev.values)
    ):
        rows = None  # row space changed: init depends on N → full rerun
    if rows is None:
        engine = None  # full rerun must re-pin at the current epoch
    elif engine is not None:
        try:
            engine.refresh_rows(rows)
        except ValueError:
            engine = None  # shard node counts moved under us
    if engine is None:
        engine = _make_engine(graph, prev.params)
        if int(engine.num_rows) != len(prev.values):
            rows = None
    if rows is not None:
        # a mutated SRC row changes the normalized weight (and the label
        # messages) of EVERY edge it emits — its out-neighbors' in-edge
        # view changed too, so they are structurally dirty as well
        n = engine.num_rows
        rows = rows[(rows >= 0) & (rows < n)]
        rows = np.union1d(rows, _out_neighbors(engine, rows))
    kwargs = {
        k: v for k, v in prev.params.items()
        if k not in ("symmetric",)
    }
    return _ALGOS[prev.algo](
        graph,
        engine=engine,
        keep_trajectory=keep_trajectory,
        _prev=prev if rows is not None else None,
        _struct_dirty=rows,
        **kwargs,
    )
