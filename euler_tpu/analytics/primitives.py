"""Bulk-synchronous whole-graph primitives (ISSUE 12 tentpole).

Euler 2.0's third pillar is whole-graph computation; DrJAX (PAPERS.md
arxiv 2403.07128) shows MapReduce-style broadcast/map/reduce building
blocks compose cleanly over sharded array state. This module is that
layer for our per-shard CSR partitions:

  ``WholeGraphEngine``   pins one published graph epoch, pulls every
      shard's out-adjacency once (local arrays in-process, the bulk
      ``edges_by_rows`` verb over the wire), and repartitions the edge
      list by DESTINATION owner into reduction-ready parts.
  ``ShardedFrontier``    per-shard dense f64 vertex state, host- or
      device-resident (f64 staged under jax's x64 context so device and
      host paths stay bit-identical).
  ``broadcast`` / ``map_shards`` / ``reduce_scatter_frontier``
      the BSP step: materialize the global frontier, run a per-part
      kernel producing (row, key, val) messages, reduce them per
      destination row — locally or via the ``frontier_exchange`` verb on
      the owning shard's server.

Bit-determinism across shard counts is the load-bearing property and it
is bought entirely with ORDER, never with tolerance: every part's edges
are lexsorted by (dst_local_row, src_node_id, edge_type, weight_bits) —
all shard-count-independent keys — and ``reduce_messages`` reduces each
row's segment left-to-right in that order. The same function serves the
in-process fast path and the server's ``frontier_exchange`` arm, so
local and remote execution agree bit-for-bit by construction.

Epoch consistency: the engine captures the shard list and their arrays
at construction. A concurrent ``GraphWriter.publish`` swaps the facade's
shard references but never mutates the pinned stores, so a running
sweep keeps computing against exactly the epoch it pinned.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.distributed.errors import RpcError

# Client-side verb table for the analytics lane — graftlint's
# wire-protocol checker and tests/test_wire_parity.py union this with
# RemoteShard/GraphWriter/query-plan tables against the server's
# HANDLED_VERBS gate. `frontier_exchange` is sent from THIS module (the
# engine ships boundary messages straight to the owning shard);
# `edges_by_rows` rides the RemoteShard client method.
WIRE_VERBS = frozenset({"frontier_exchange"})

_MSG_BYTES = 24  # one (row i64, key i64, val f64) message on the wire


def _f64_bits(vals: np.ndarray) -> np.ndarray:
    """Total-order sort key for f64 (bit pattern): not numeric order —
    just ANY canonical order so equal multisets sort identically
    regardless of which shard contributed which element."""
    return np.ascontiguousarray(np.asarray(vals, np.float64)).view(np.uint64)


def _ragged_take(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Element indices of the ragged slices [starts[i], starts[i]+lens[i])."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, np.int64)
    out = np.repeat(starts.astype(np.int64), lens)
    step = np.arange(total, dtype=np.int64)
    step -= np.repeat(np.cumsum(lens, dtype=np.int64) - lens, lens)
    return out + step


def reduce_messages(rows, keys, vals, mode: str):
    """Deterministically reduce (row, key, val) messages per row.

    The ONE reduction everybody shares — the engine's in-process path
    and the server's ``frontier_exchange`` dispatch arm both land here,
    which is what makes local and remote execution bit-identical.

    Canonical order: lexsort by (val_bits, key, row) — row-major
    segments, ties broken by key then by the value's bit pattern, so any
    permutation of the same message multiset reduces identically.

    mode: "sum" (left-to-right f64 segment sums), "min" (segment
    minima), "vote" (per-(row, key) weight sums, winner = highest sum,
    ties to the smallest key).

    Returns (rows u. i64 ascending, vals f64, keys i64): for sum/min the
    reduced value per row (keys zeros); for vote the winning key per row
    (vals = the winning weight sum).
    """
    rows = np.asarray(rows, np.int64)
    keys = np.asarray(keys, np.int64)
    vals = np.asarray(vals, np.float64)
    if len(rows) == 0:
        e = np.empty(0, np.int64)
        return e, np.empty(0, np.float64), np.empty(0, np.int64)
    order = np.lexsort((_f64_bits(vals), keys, rows))
    r, k, v = rows[order], keys[order], vals[order]
    if mode in ("sum", "min"):
        uniq, starts = np.unique(r, return_index=True)
        if mode == "sum":
            # np.bincount accumulates in data order — the lexsorted
            # canonical order — so the per-row sum is an ordered
            # left-to-right reduction, not an unordered one
            dense = np.bincount(
                np.searchsorted(uniq, r), weights=v, minlength=len(uniq)
            )
            return uniq, dense.astype(np.float64), np.zeros(len(uniq), np.int64)
        return uniq, np.minimum.reduceat(v, starts), np.zeros(len(uniq), np.int64)
    if mode != "vote":
        raise ValueError(f"unknown reduce mode {mode!r}")
    # vote: sum val per (row, key) group, then argmax per row with ties
    # going to the smallest key — all comparisons, no accumulation races
    grp = np.flatnonzero(np.diff(r) | np.diff(k))
    starts = np.concatenate([[0], grp + 1])
    gr, gk = r[starts], k[starts]
    gsum = np.add.reduceat(v, starts)
    pick = np.lexsort((gk, -gsum, gr))
    gr, gk, gsum = gr[pick], gk[pick], gsum[pick]
    uniq, first = np.unique(gr, return_index=True)
    return uniq, gsum[first], gk[first]


def stage_frontier_part(values: np.ndarray):
    """Stage one frontier shard's f64 state on device (delegates to
    dataflow/device so the device-residency policy lives in one place);
    returns the host array unchanged when x64 staging is unavailable."""
    from euler_tpu.dataflow import device as _device

    return _device.stage_frontier(values)


class ShardedFrontier:
    """Per-shard dense vertex state (f64), host- or device-resident.

    ``offsets`` is the shard-major global row map (cumsum of per-shard
    node counts); part p holds rows [offsets[p], offsets[p+1]).
    Memory per shard is N/shards * 8 bytes — the frontier stays sharded
    and only ``to_global`` materializes the full vector (SCALE.md).
    """

    def __init__(self, offsets: np.ndarray, values=None, device: bool = False):
        self.offsets = np.asarray(offsets, np.int64)
        self.device = bool(device)
        n = int(self.offsets[-1])
        if values is None:
            values = np.zeros(n, np.float64)
        values = np.asarray(values, np.float64)
        if len(values) != n:
            raise ValueError(
                f"frontier length {len(values)} != row space {n}"
            )
        self.parts = []
        for p in range(len(self.offsets) - 1):
            part = np.ascontiguousarray(
                values[self.offsets[p]:self.offsets[p + 1]]
            )
            self.parts.append(
                stage_frontier_part(part) if self.device else part
            )

    @classmethod
    def from_global(cls, offsets, values, device=False):
        return cls(offsets, values, device=device)

    @property
    def num_rows(self) -> int:
        return int(self.offsets[-1])

    def to_global(self) -> np.ndarray:
        """Materialize the full f64 vector on the host (shard-major)."""
        if not self.parts:
            return np.zeros(0, np.float64)
        return np.concatenate([np.asarray(p, np.float64) for p in self.parts])


def broadcast(frontier: ShardedFrontier) -> np.ndarray:
    """BSP broadcast: every shard's kernel sees the full frontier."""
    return frontier.to_global()


def map_shards(engine, fn, parts=None):
    """Run ``fn(part_index, part)`` over the engine's edge parts,
    collecting per-part results in shard order (deterministic)."""
    parts = engine.parts if parts is None else parts
    return [fn(p, part) for p, part in enumerate(parts)]


def reduce_scatter_frontier(engine, messages, mode: str, out: np.ndarray):
    """Reduce per-part (rows_local, keys, vals) messages into ``out``
    (a global f64 vector), via the owning shard's ``frontier_exchange``
    verb when the engine runs in remote-exchange mode. Rows with no
    messages keep their prior value in ``out``. Returns the global rows
    that received a reduction (and, for vote mode, writes winning keys
    as f64 values)."""
    touched = []
    for p, msg in enumerate(messages):
        if msg is None:
            continue
        rows, keys, vals = msg
        if len(rows) == 0:
            continue
        u, v, k = engine.exchange(p, rows, keys, vals, mode)
        g = u + engine.offsets[p]
        out[g] = k.astype(np.float64) if mode == "vote" else v
        touched.append(g)
    if not touched:
        return np.empty(0, np.int64)
    return np.concatenate(touched)


class WholeGraphEngine:
    """Pinned-epoch whole-graph view: per-shard CSR export repartitioned
    by destination owner into reduction-ready parts.

    exchange: "auto" reduces in-process for local shards and via the
    ``frontier_exchange`` verb for remote ones; "local" never leaves the
    process; "remote" forces the verb wherever the shard has a wire
    (falling back per shard on old servers' unknown-op answers).
    """

    def __init__(
        self,
        graph,
        edge_types=None,
        device: bool = False,
        exchange: str = "auto",
        rows_per_call: int = 65536,
        symmetric: bool = False,
    ):
        if exchange not in ("auto", "local", "remote"):
            raise ValueError(f"exchange mode {exchange!r}")
        self.graph = graph
        self.edge_types = (
            None if edge_types is None
            else [int(t) for t in edge_types]
        )
        self.device = bool(device)
        self.exchange_mode = exchange
        self.rows_per_call = max(int(rows_per_call), 1)
        self.symmetric = bool(symmetric)
        # pin the shard list NOW: publish swaps the facade's references
        # but never mutates the stores behind them, so this engine keeps
        # reading exactly the epoch it pinned even under live writers
        self._shards = list(graph.shards)
        self.num_shards = len(self._shards)
        self._exchange_wire = [True] * self.num_shards
        self.stats = {
            "rows_fetched": 0,
            "rows_refetched": 0,
            "exchange_bytes": 0,
            "exchange_calls": 0,
            "dropped_edges": 0,
        }
        self._shard_n = [int(s.num_nodes) for s in self._shards]
        self.offsets = np.cumsum([0] + self._shard_n).astype(np.int64)
        self.num_rows = int(self.offsets[-1])
        self.node_ids = np.concatenate(
            [self._shard_node_ids(p) for p in range(self.num_shards)]
        ) if self.num_rows else np.empty(0, np.uint64)
        # raw per-shard out-adjacency: (counts, dst_ids, w_f64, types)
        self._raw = [
            self._fetch_rows(p, np.arange(self._shard_n[p], dtype=np.int64))
            for p in range(self.num_shards)
        ]
        self.stats["rows_fetched"] = self.num_rows
        self.epoch_pin = self._read_epochs()
        self._build()

    # -- per-shard data plane -------------------------------------------

    def _shard_node_ids(self, p: int) -> np.ndarray:
        sh = self._shards[p]
        if not hasattr(sh, "call"):
            return np.asarray(sh.node_ids, np.uint64)
        n = self._shard_n[p]
        chunks = []
        for lo in range(0, n, self.rows_per_call):
            rows = np.arange(
                lo, min(lo + self.rows_per_call, n), dtype=np.int64
            )
            chunks.append(np.asarray(sh.ids_by_rows(rows)[0], np.uint64))
        return (
            np.concatenate(chunks) if chunks else np.empty(0, np.uint64)
        )

    def _fetch_rows(self, p: int, rows: np.ndarray):
        """Out-adjacency export for `rows` of shard p: (counts i64,
        dst_ids u64, w f64, types i32), type-major per row — local array
        slices in-process, the ``edges_by_rows`` bulk verb on the wire
        (chunked; RemoteShard degrades to per-row fallback on old
        servers)."""
        sh = self._shards[p]
        if hasattr(sh, "call"):
            counts, dst, w, tt = [], [], [], []
            for lo in range(0, len(rows), self.rows_per_call):
                sub = rows[lo:lo + self.rows_per_call]
                c, d, ww, t = sh.edges_by_rows(sub, self.edge_types)
                counts.append(np.asarray(c, np.int64))
                dst.append(np.asarray(d, np.uint64))
                w.append(np.asarray(ww, np.float64))
                tt.append(np.asarray(t, np.int32))
            if not counts:
                return (np.empty(0, np.int64), np.empty(0, np.uint64),
                        np.empty(0, np.float64), np.empty(0, np.int32))
            return (np.concatenate(counts), np.concatenate(dst),
                    np.concatenate(w), np.concatenate(tt))
        types = (
            range(len(sh.adj)) if self.edge_types is None
            else [t for t in self.edge_types if 0 <= t < len(sh.adj)]
        )
        row_pos, dst, w, tt = [], [], [], []
        for t in types:
            c = sh.adj[t]
            lens = c.degrees(rows)
            idx = _ragged_take(c.indptr[rows].astype(np.int64), lens)
            row_pos.append(np.repeat(np.arange(len(rows), dtype=np.int64), lens))
            dst.append(np.asarray(c.dst[idx], np.uint64))
            w.append(np.asarray(c.w[idx], np.float64))
            tt.append(np.full(len(idx), t, np.int32))
        if not row_pos:
            return (np.zeros(len(rows), np.int64), np.empty(0, np.uint64),
                    np.empty(0, np.float64), np.empty(0, np.int32))
        row_pos = np.concatenate(row_pos)
        dst = np.concatenate(dst)
        w = np.concatenate(w)
        tt = np.concatenate(tt)
        # type-major per row, preserving within-type CSR order — the
        # same layout the edges_by_rows server arm ships
        order = np.lexsort((tt, row_pos))
        counts = np.bincount(row_pos, minlength=len(rows)).astype(np.int64)
        return counts, dst[order], w[order], tt[order]

    def _read_epochs(self) -> tuple:
        pins = []
        for sh in self._shards:
            if hasattr(sh, "call"):
                pins.append(int(sh.stats().get("graph_epoch", 0)))
            else:
                pins.append(int(getattr(sh, "graph_epoch", 0)))
        return tuple(pins)

    # -- derived edge partitions ----------------------------------------

    def _build(self):
        """Globalize the raw per-shard edge lists and partition by
        destination owner, each part lexsorted into canonical reduction
        order — (dst_local, src_node_id, type, weight_bits): every key
        is shard-count independent, so a row's segment reduces to the
        same bits no matter how the graph is partitioned."""
        srcs, dsts, ws, tts, src_ids = [], [], [], [], []
        for p in range(self.num_shards):
            counts, dst_ids, w, tt = self._raw[p]
            local = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
            srcs.append(local + self.offsets[p])
            ids_p = self.node_ids[self.offsets[p]:self.offsets[p + 1]]
            src_ids.append(np.repeat(ids_p, counts))
            dsts.append(dst_ids)
            ws.append(w)
            tts.append(tt)
        src = np.concatenate(srcs) if srcs else np.empty(0, np.int64)
        src_id = (
            np.concatenate(src_ids) if src_ids else np.empty(0, np.uint64)
        )
        dst_id = np.concatenate(dsts) if dsts else np.empty(0, np.uint64)
        w = np.concatenate(ws) if ws else np.empty(0, np.float64)
        tt = np.concatenate(tts) if tts else np.empty(0, np.int32)
        # resolve destination rows from the PINNED id table (the facade's
        # lookup would chase post-publish state)
        id_order = np.argsort(self.node_ids, kind="stable")
        ids_sorted = self.node_ids[id_order]
        pos = np.searchsorted(ids_sorted, dst_id)
        pos = np.clip(pos, 0, max(len(ids_sorted) - 1, 0))
        if len(dst_id) and len(ids_sorted):
            found = ids_sorted[pos] == dst_id
            dst = np.where(found, id_order[pos], -1).astype(np.int64)
        else:
            found = np.zeros(len(dst_id), bool)
            dst = np.full(len(dst_id), -1, np.int64)
        self.stats["dropped_edges"] = int(len(dst_id) - found.sum())
        keep = dst >= 0
        src, src_id, dst, w, tt = (
            src[keep], src_id[keep], dst[keep], w[keep], tt[keep]
        )
        self.edge_src = src
        self.edge_dst = dst
        self.edge_src_id = src_id
        self.edge_w = w
        self.edge_tt = tt
        if self.symmetric:
            # undirected view: every edge also propagates dst → src
            src = np.concatenate([self.edge_src, self.edge_dst])
            dst = np.concatenate([self.edge_dst, self.edge_src])
            src_id = np.concatenate(
                [self.edge_src_id, self.node_ids[self.edge_dst]]
            )
            w = np.concatenate([self.edge_w, self.edge_w])
            tt = np.concatenate([self.edge_tt, self.edge_tt])
        self.num_edges = len(src)
        owner = np.searchsorted(self.offsets, dst, side="right") - 1
        self.boundary_edges = int(
            (owner != np.searchsorted(self.offsets, src, side="right") - 1)
            .sum()
        )
        # weighted out-degree sums in canonical (src, dst_id, type,
        # w_bits) order — the PageRank normalizer, bit-stable across
        # shard counts for the same reason the parts are
        dst_ids_all = self.node_ids[dst] if len(dst) else np.empty(0, np.uint64)
        o = np.lexsort((_f64_bits(w), tt, dst_ids_all, src))
        self.out_w = np.bincount(
            src[o], weights=w[o], minlength=self.num_rows
        ).astype(np.float64)
        self.parts = []
        for p in range(self.num_shards):
            sel = owner == p
            ps, pd, pid, pw, ptt = (
                src[sel], dst[sel], src_id[sel], w[sel], tt[sel]
            )
            dloc = pd - self.offsets[p]
            o = np.lexsort((_f64_bits(pw), ptt, pid, dloc))
            n_p = self._shard_n[p]
            dloc = dloc[o]
            indptr = np.searchsorted(dloc, np.arange(n_p + 1, dtype=np.int64))
            self.parts.append({
                "indptr": indptr.astype(np.int64),
                "dst_local": dloc.astype(np.int64),
                "src": ps[o].astype(np.int64),
                "w": pw[o],
                "tt": ptt[o].astype(np.int32),
            })
        # src-grouped out-rows CSR for incremental dirty propagation
        o = np.argsort(src, kind="stable")
        self._out_indptr = np.searchsorted(
            src[o], np.arange(self.num_rows + 1, dtype=np.int64)
        ).astype(np.int64)
        self._out_dst = dst[o].astype(np.int64)

    # -- incremental refresh --------------------------------------------

    def refresh_rows(self, mutated_global_rows: np.ndarray) -> None:
        """Re-read ONLY the mutated rows' adjacency from the (new-epoch)
        shards and rebuild the derived partitions — the data-plane half
        of ``rerun_incremental``. Raises ValueError if any shard's node
        count moved (the row space changed; callers fall back to a full
        engine rebuild)."""
        rows = np.unique(np.asarray(mutated_global_rows, np.int64))
        self._shards = list(self.graph.shards)
        for p, sh in enumerate(self._shards):
            if int(sh.num_nodes) != self._shard_n[p]:
                raise ValueError(
                    f"shard {p} node count moved "
                    f"({self._shard_n[p]} -> {int(sh.num_nodes)})"
                )
        for p in range(self.num_shards):
            local = rows[(rows >= self.offsets[p])
                         & (rows < self.offsets[p + 1])] - self.offsets[p]
            if len(local) == 0:
                continue
            counts, dst, w, tt = self._raw[p]
            new_c, new_d, new_w, new_t = self._fetch_rows(p, local)
            self.stats["rows_refetched"] += len(local)
            # ragged row splice: cut each mutated row's old slice out,
            # splice the refetched one in
            starts = np.concatenate(
                [[0], np.cumsum(counts, dtype=np.int64)]
            )
            keep = np.ones(int(starts[-1]), bool)
            keep[_ragged_take(starts[local], counts[local])] = False
            parts_d = [new_d, dst[keep]]
            parts_w = [new_w, w[keep]]
            parts_t = [new_t, tt[keep]]
            # rebuild type-major-per-row order over the merged list
            row_pos = np.concatenate([
                np.repeat(local, new_c),
                np.repeat(np.arange(len(counts), dtype=np.int64),
                          counts)[keep],
            ])
            d = np.concatenate(parts_d)
            ww = np.concatenate(parts_w)
            t = np.concatenate(parts_t)
            order = np.lexsort((t, row_pos))
            merged_counts = counts.copy()
            merged_counts[local] = new_c
            self._raw[p] = (
                merged_counts, d[order], ww[order], t[order]
            )
        self.epoch_pin = self._read_epochs()
        self._build()

    # -- reduction plane -------------------------------------------------

    def exchange(self, p: int, rows, keys, vals, mode: str):
        """Reduce one part's messages on the owning shard — remotely via
        ``frontier_exchange`` (deadline envelope + borrow-mode decode
        ride the normal call path) or in-process through the SAME
        ``reduce_messages``, so both transports agree bit-for-bit. Old
        servers answer unknown-op; the engine degrades that shard to the
        local path once and stays there (sticky)."""
        sh = self._shards[p]
        remote_ok = (
            hasattr(sh, "call")
            and self.exchange_mode != "local"
            and self._exchange_wire[p]
        )
        self.stats["exchange_bytes"] += len(rows) * _MSG_BYTES
        if remote_ok:
            try:
                u, v, k = sh.call(
                    "frontier_exchange",
                    [np.asarray(rows, np.int64),
                     np.asarray(keys, np.int64),
                     np.asarray(vals, np.float64), mode],
                )
                self.stats["exchange_calls"] += 1
                return (np.asarray(u, np.int64), np.asarray(v, np.float64),
                        np.asarray(k, np.int64))
            except RpcError as e:
                if "unknown op" not in str(e):
                    raise
                self._exchange_wire[p] = False  # sticky old-server degrade
        return reduce_messages(rows, keys, vals, mode)

    # -- kernels ---------------------------------------------------------

    def gather_edges(self, p: int, rows_local=None):
        """Message slots for part p: (msg_rows, edge_idx) covering the
        given local rows' in-edge segments (all rows when None). The
        edge index doubles as the exchange KEY — it encodes the part's
        canonical order, so subset, full, local and remote reductions
        all see identical per-row orderings."""
        part = self.parts[p]
        if rows_local is None:
            idx = np.arange(len(part["src"]), dtype=np.int64)
            return part["dst_local"], idx
        rows_local = np.asarray(rows_local, np.int64)
        starts = part["indptr"][rows_local]
        lens = part["indptr"][rows_local + 1] - starts
        idx = _ragged_take(starts, lens)
        return np.repeat(rows_local, lens), idx

    def contrib(self, p: int, edge_idx: np.ndarray, global_vec, weights):
        """Per-edge contribution weights[e] * frontier[src[e]] — the
        elementwise half of a BSP step. Host numpy by default; with
        device=True the multiply runs as f64 jax ops (elementwise IEEE,
        bit-identical to numpy) over the staged frontier."""
        src = self.parts[p]["src"][edge_idx]
        w = weights[edge_idx]
        if self.device:
            from euler_tpu.dataflow import device as _device

            out = _device.frontier_contrib(w, global_vec, src)
            if out is not None:
                return out
        return w * np.asarray(global_vec, np.float64)[src]

    def by_id(self, values: np.ndarray):
        """(node_ids ascending, values) — the shard-count-independent
        presentation every parity test compares on."""
        order = np.argsort(self.node_ids, kind="stable")
        return self.node_ids[order], np.asarray(values)[order]
