from euler_tpu.utils.hooks import SyncExit  # noqa: F401
from euler_tpu.utils.file_io import exists, list_dir, open_file  # noqa: F401
