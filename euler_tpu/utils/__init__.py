from euler_tpu.utils.hooks import SyncExit  # noqa: F401
