"""Unified file IO over local FS and HDFS.

The reference abstracts storage behind FileIO with local and libhdfs
implementations (euler/common/file_io.h, local_file_io.cc, hdfs_file_io.cc)
so graph data and sample files can live on either. Here the same seam is a
path-scheme dispatch: `hdfs://` paths go through pyarrow's HadoopFileSystem
when available (gated — this image has no HDFS), everything else through
the local filesystem.

Tested contract (pinned; see tests/test_backends_io.py): the hdfs branch
is exercised against a STUB pyarrow.fs backed by a local dir — covering
scheme dispatch, URI→(filesystem, path) resolution, input/append/output
stream selection, text wrapping, exists()/listdir()/walk translation, and
the no-pyarrow RuntimeError gate. What is asserted is therefore exactly
the adapter logic between this module and the pyarrow FileSystem API
surface it calls (open_input_stream / open_append_stream /
open_output_stream / get_file_info / FileSelector). It has NOT been run
against a real HDFS namenode: pyarrow's own libhdfs binding is trusted to
implement that API; connection config (HADOOP_HOME, CLASSPATH,
fs.defaultFS) is the deployment's responsibility. Anyone wiring a real
cluster should run tests/test_backends_io.py's roundtrip against an
hdfs:// URI as the acceptance check — the test body is cluster-agnostic.
"""

from __future__ import annotations

import os


def _is_hdfs(path: str) -> bool:
    return path.startswith("hdfs://")


def _hdfs_fs(path: str):
    """(filesystem, fs-local path) for an hdfs://[host:port]/... URI.

    The filesystem connects to the authority named in the path itself (or
    fs.defaultFS when the path has none), so explicit namenode addresses
    resolve against the right cluster.
    """
    try:
        from pyarrow import fs as pafs

        filesystem, p = pafs.FileSystem.from_uri(path)
        return filesystem, p
    except Exception as e:  # gated: no libhdfs/Hadoop in this image
        raise RuntimeError(
            "hdfs:// paths need pyarrow with libhdfs; install pyarrow and "
            "set HADOOP_HOME/CLASSPATH, or copy the data to local disk"
        ) from e


def open_file(path: str, mode: str = "rb"):
    """open() across local and hdfs:// paths (FileIO::NewFileIO parity).

    HDFS supports read ("r"/"rb"), truncating write ("w"/"wb"), and append
    ("a"/"ab"); update modes ("r+", "w+") are local-only.
    """
    if not _is_hdfs(path):
        return open(path, mode)
    if "+" in mode:
        raise ValueError(f"update mode {mode!r} is not supported on hdfs://")
    fs, p = _hdfs_fs(path)
    if "r" in mode:
        stream = fs.open_input_stream(p)
    elif "a" in mode:
        stream = fs.open_append_stream(p)
    else:
        stream = fs.open_output_stream(p)
    if "b" not in mode:
        import io

        return io.TextIOWrapper(stream)
    return stream


def list_dir(path: str) -> list[str]:
    """Directory entries (names only), local or hdfs://."""
    if not _is_hdfs(path):
        return sorted(os.listdir(path))
    fs, p = _hdfs_fs(path)
    from pyarrow import fs as pafs

    infos = fs.get_file_info(pafs.FileSelector(p))
    return sorted(os.path.basename(i.path) for i in infos)


def exists(path: str) -> bool:
    if not _is_hdfs(path):
        return os.path.exists(path)
    fs, p = _hdfs_fs(path)
    from pyarrow import fs as pafs

    return fs.get_file_info(p).type != pafs.FileType.NotFound
