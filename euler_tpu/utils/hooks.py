"""Training-run coordination utilities.

`SyncExit` is the reference's SyncExitHook (tf_euler/python/utils/hooks.py:
25-35): in a multi-worker run each worker marks itself done on the shared
filesystem; the chief blocks until all have exited before tearing down
shared services. The PS variable counter becomes marker files next to the
membership registry.
"""

from __future__ import annotations

import os
import time


class SyncExit:
    def __init__(self, path: str, worker: int, num_workers: int):
        self.path = path
        self.worker = worker
        self.num_workers = num_workers
        os.makedirs(path, exist_ok=True)

    def mark_done(self):
        with open(os.path.join(self.path, f"done_{self.worker}"), "w") as f:
            f.write(str(time.time()))

    def wait_all(self, timeout: float = 600.0, poll: float = 0.5):
        deadline = time.time() + timeout
        done = 0
        while time.time() < deadline:
            done = sum(
                os.path.exists(os.path.join(self.path, f"done_{w}"))
                for w in range(self.num_workers)
            )
            if done >= self.num_workers:
                return True
            time.sleep(poll)
        raise TimeoutError(
            f"sync_exit: only {done}/{self.num_workers} workers done"
        )
