"""Composable solution pipelines (tf_euler/python/solution parity).

The reference builds supervised/unsupervised models from four pluggable
parts — (get_label_fn, encoder_fn, logit_fn, loss_fn)
(solution/base_supervise.py:26-50). Here a Solution is a flax module wired
from the same parts: an encoder module, a logits head, and a loss; samplers
come from the estimator batch sources.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.nn.metrics import METRICS


class SampleNegWithTypes:
    """Global negative sampler per root (solution/samplers.py parity):
    num_negs nodes of each requested type, [B, num_negs] per type."""

    def __init__(self, graph, neg_type, num_negs: int = 5, rng=None):
        import numpy as np

        self.graph = graph
        self.neg_types = neg_type if isinstance(neg_type, list) else [neg_type]
        self.num_negs = num_negs
        self.rng = rng if rng is not None else np.random.default_rng()

    def __call__(self, inputs):
        b = len(inputs)
        groups = [
            self.graph.sample_node(
                b * self.num_negs, t, rng=self.rng
            ).reshape(b, self.num_negs)
            for t in self.neg_types
        ]
        return groups[0] if len(groups) == 1 else groups


class SamplePosWithTypes:
    """Positive-context sampler (solution/samplers.py parity): num_pos
    sampled neighbors over the given edge types, [B, num_pos]."""

    def __init__(self, graph, edge_type, num_pos: int = 1, rng=None):
        import numpy as np

        self.graph = graph
        self.edge_types = (
            edge_type if isinstance(edge_type, list) else [edge_type]
        )
        self.num_pos = num_pos
        self.rng = rng if rng is not None else np.random.default_rng()

    def __call__(self, inputs):
        nbr, _, _, _, _ = self.graph.sample_neighbor(
            inputs, self.edge_types, self.num_pos, rng=self.rng
        )
        return nbr


class DenseLogits(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, emb, *_):
        return nn.Dense(self.num_classes)(emb)


class CosineLogits(nn.Module):
    """Cosine similarity between two embeddings (logits.py parity)."""

    scale: float = 10.0

    @nn.compact
    def __call__(self, a, b):
        na = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True), 1e-9)
        nb = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True), 1e-9)
        return self.scale * jnp.sum(na * nb, axis=-1)


class PosNegLogits(nn.Module):
    """[pos | negs] logit matrix from (src, pos, negs) embeddings."""

    @nn.compact
    def __call__(self, src, pos, negs):
        b, d = src.shape
        negs = negs.reshape(b, -1, d)
        pos_l = jnp.sum(src * pos, axis=-1)
        neg_l = jnp.einsum("bd,bnd->bn", src, negs)
        return jnp.concatenate([pos_l[:, None], neg_l], axis=1)


def sigmoid_loss(logits, labels):
    return jnp.mean(
        jnp.sum(optax.sigmoid_binary_cross_entropy(logits, labels), axis=-1)
    )


def softmax_loss(logits, labels):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    )


LOSSES = {"sigmoid": sigmoid_loss, "softmax": softmax_loss}


class SuperviseSolution(nn.Module):
    """encoder → logits → loss with a configurable metric."""

    encoder: nn.Module
    num_classes: int
    loss: str = "sigmoid"
    metric: str = "f1"

    def setup(self):
        self.head = DenseLogits(self.num_classes)

    def embed(self, batch):
        return self.encoder(batch)

    def __call__(self, batch):
        emb = self.encoder(batch)
        logits = self.head(emb)
        labels = batch.labels
        if self.loss == "softmax":
            loss = softmax_loss(logits, jnp.argmax(labels, -1))
        else:
            loss = sigmoid_loss(logits, labels)
        metric = METRICS[self.metric](labels, logits)
        return emb, loss, self.metric, metric


class UnsuperviseSolution(nn.Module):
    """encoder + PosNegLogits + softmax ranking loss, MRR metric."""

    encoder: nn.Module

    def setup(self):
        self.logits = PosNegLogits()

    def embed(self, batch):
        return self.encoder(batch)

    def __call__(self, src, pos, negs):
        from euler_tpu.nn.metrics import mrr

        e_s = self.encoder(src)
        e_p = self.encoder(pos)
        e_n = self.encoder(negs)
        logits = self.logits(e_s, e_p, e_n)
        labels = jnp.zeros(e_s.shape[0], dtype=jnp.int32)
        loss = softmax_loss(logits, labels)
        return e_s, loss, "mrr", mrr(logits[:, 0], logits[:, 1:])
