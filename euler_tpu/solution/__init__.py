"""Composable solution pipelines (tf_euler/python/solution parity).

The reference builds supervised/unsupervised models from four pluggable
parts — (get_label_fn, encoder_fn, logit_fn, loss_fn)
(solution/base_supervise.py:26-50). Here a Solution is a flax module wired
from the same parts: an encoder module, a logits head, and a loss; samplers
come from the estimator batch sources.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.nn.metrics import METRICS


class DenseLogits(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, emb, *_):
        return nn.Dense(self.num_classes)(emb)


class CosineLogits(nn.Module):
    """Cosine similarity between two embeddings (logits.py parity)."""

    scale: float = 10.0

    @nn.compact
    def __call__(self, a, b):
        na = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True), 1e-9)
        nb = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True), 1e-9)
        return self.scale * jnp.sum(na * nb, axis=-1)


class PosNegLogits(nn.Module):
    """[pos | negs] logit matrix from (src, pos, negs) embeddings."""

    @nn.compact
    def __call__(self, src, pos, negs):
        b, d = src.shape
        negs = negs.reshape(b, -1, d)
        pos_l = jnp.sum(src * pos, axis=-1)
        neg_l = jnp.einsum("bd,bnd->bn", src, negs)
        return jnp.concatenate([pos_l[:, None], neg_l], axis=1)


def sigmoid_loss(logits, labels):
    return jnp.mean(
        jnp.sum(optax.sigmoid_binary_cross_entropy(logits, labels), axis=-1)
    )


def softmax_loss(logits, labels):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    )


LOSSES = {"sigmoid": sigmoid_loss, "softmax": softmax_loss}


class SuperviseSolution(nn.Module):
    """encoder → logits → loss with a configurable metric."""

    encoder: nn.Module
    num_classes: int
    loss: str = "sigmoid"
    metric: str = "f1"

    def setup(self):
        self.head = DenseLogits(self.num_classes)

    def embed(self, batch):
        return self.encoder(batch)

    def __call__(self, batch):
        emb = self.encoder(batch)
        logits = self.head(emb)
        labels = batch.labels
        if self.loss == "softmax":
            loss = softmax_loss(logits, jnp.argmax(labels, -1))
        else:
            loss = sigmoid_loss(logits, labels)
        metric = METRICS[self.metric](labels, logits)
        return emb, loss, self.metric, metric


class UnsuperviseSolution(nn.Module):
    """encoder + PosNegLogits + softmax ranking loss, MRR metric."""

    encoder: nn.Module

    def setup(self):
        self.logits = PosNegLogits()

    def embed(self, batch):
        return self.encoder(batch)

    def __call__(self, src, pos, negs):
        from euler_tpu.nn.metrics import mrr

        e_s = self.encoder(src)
        e_p = self.encoder(pos)
        e_n = self.encoder(negs)
        logits = self.logits(e_s, e_p, e_n)
        labels = jnp.zeros(e_s.shape[0], dtype=jnp.int32)
        loss = softmax_loss(logits, labels)
        return e_s, loss, "mrr", mrr(logits[:, 0], logits[:, 1:])
