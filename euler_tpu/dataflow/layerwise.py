"""Layerwise (LADIES / FastGCN) dataflows.

The reference bounds fanout blow-up with layerwise sampling
(API_SAMPLE_L, sample_layer_op.cc:83; python neighbor_ops.py:359-366;
LayerwiseDataFlow / FastDataFlow). The TPU form is even more natural: each
layer is ONE fixed-size candidate set shared by the whole batch, and the
inter-layer adjacency is a dense [n_l, n_{l+1}] weight matrix — aggregation
becomes a plain matmul on the MXU instead of gather/scatter.
"""

from __future__ import annotations

import flax.struct
import jax
import numpy as np

from euler_tpu.dataflow.base import DataFlow
from euler_tpu.graph.store import DEFAULT_ID

Array = jax.Array


@flax.struct.dataclass
class LayerwiseBatch:
    """Dense-adjacency multi-layer batch.

    feats[l]  — f32[N_l, F] features of layer l (layer 0 = roots)
    masks[l]  — bool[N_l]
    adjs[l]   — f32[N_l, N_{l+1}] weighted adjacency layer l ← l+1
    """

    feats: tuple
    masks: tuple
    adjs: tuple
    root_idx: Array
    labels: Array | None = None
    hop_ids: tuple | None = None


class LayerwiseDataFlow(DataFlow):
    """LADIES-style: candidates sampled ∝ incident weight from the batch."""

    def __init__(
        self,
        graph,
        feature_names,
        edge_types=None,
        layer_sizes=(128, 128),
        label_feature=None,
        label_dim=None,
        normalize: bool = True,
        rng=None,
        feature_mode="dense",
    ):
        super().__init__(
            graph, feature_names, label_feature, label_dim, rng, feature_mode
        )
        self.edge_types = edge_types
        self.layer_sizes = list(layer_sizes)
        self.normalize = normalize

    def query(self, roots: np.ndarray) -> LayerwiseBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        layer_ids = [roots]
        layer_masks = [roots != DEFAULT_ID]
        adjs = []
        cur = roots
        for count in self.layer_sizes:
            layer, adj, lmask = self.graph.sample_neighbor_layerwise(
                cur, self.edge_types, count=count, rng=self.rng
            )
            if self.normalize:
                row = adj.sum(axis=1, keepdims=True)
                adj = adj / np.maximum(row, 1e-9)
            adjs.append(adj.astype(np.float32))
            layer_ids.append(layer)
            layer_masks.append(lmask)
            cur = layer
        feats = tuple(self.node_feats(ids) for ids in layer_ids)
        return LayerwiseBatch(
            feats=feats,
            masks=tuple(layer_masks),
            adjs=tuple(adjs),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in layer_ids
            ),
        )
