"""Fully on-device graph sampling (GraphSAGE fanouts + random walks).

The host flows (sage.py, walk.py) sample subgraphs and walks on the CPU
and ship int32 feature rows over PCIe/network every step — the lean wire
minimizes the bytes, but a tunneled or remote device still pays
per-dispatch transfer for ~10^5 rows/step. This module removes the wire
entirely: the padded adjacency lives in HBM next to the feature cache,
and every step of the scanned train loop *traces* root sampling +
multi-hop fanout (or walk + skip-gram pair generation) as XLA ops.
Per-step host→device traffic is zero; the only inputs are PRNG keys.

This is the TPU-first answer to the reference's sample_fanout and
random_walk kernels (euler/core/kernels/sample_fanout_op.cc,
random_walk_op.cc, and the TF custom ops in tf_euler/python/euler_ops):
instead of a host-side C++ sampler feeding the accelerator, the sampler
IS accelerator code — a [N+1, D] int32 gather plus vectorized uniform
draws, fused by XLA into the same program as the model. Weighted graphs
are first-class: edge draws invert a per-row cumulative-weight CDF with
a [W, k, D] compare-reduce (pure VPU work; D is the guarded max degree),
and weighted root draws binary-search a uint32-quantized node-weight CDF
— the same weighted-with-replacement distribution the host samplers and
the C++ engine's alias tables draw from (graph_engine.cc `AliasTable`).
Batches from a weighted graph carry bf16 edge weights, matching the host
weighted-lean wire (sage.py `_lean_w`) leaf-for-leaf.

Memory — two layouts:

- `layout="dense"`: padded adjacency, (N+1)·Dmax·4 bytes of HBM (row+1
  encoding, 0 = padding). For bounded-degree graphs this is small (200k
  nodes × deg 15 ≈ 12 MB); power-law graphs with hub nodes blow the
  table up — `max_degree` (default 512) is a GUARD that fails
  construction loudly in that case (truncating would bias sampling).
- `layout="paged"`: ragged neighbor PAGES — fixed-size pages (default
  16 slots) in a flat HBM buffer plus a per-node page table
  (`page_start`), so a hub node spans ⌈deg/P⌉ pages instead of widening
  every row: HBM ∝ edges (+ N·4 B of page table), no `max_degree`
  failure mode. The access shape is the Ragged Paged Attention
  indirection (PAPERS.md, arxiv 2604.15464); the page reads run through
  the `paged_gather`/`paged_cdf_count` entry points in
  ops/pallas_kernels.py (Pallas on request, jitted jnp reference as the
  `auto` fallback and A/B oracle).

`layout="auto"` (the default) picks dense when the graph's max degree
fits `max_degree` and paged otherwise, for the SAGE-family flows;
flows that need the dense planes (walk bias, per-relation type planes,
layerwise scatter) always stage dense.

Weighted draws in BOTH layouts invert the same per-row uint32-quantized
CDF staged at construction (exact f64 cumsum per row, quantized once),
so paged and dense lanes draw bit-identical neighbors under the same
keys — pinned by tests/test_paged_flow.py. The parity story stays one
lane wide.

Remote graphs stage too: when the shards are RemoteShard handles the
construction sweep enumerates each shard's node table over the wire
(`ids_by_rows`) and walks the same chunked get_full_neighbor +
lookup_rows path through the Graph facade — deterministic verbs, so the
PR-5 client ReadCache serves repeats. Per-step traffic afterwards is
zero, exactly like the local staging.

Staging cost (one-time, at construction): the chunked
get_full_neighbor + lookup_rows sweep runs at ~3.7M edges/s on one host
core (0.8 s for the bench's 200k×15 graph; ~2 min per half-billion
edges) — amortized over a training run it is noise next to the
per-step wire it removes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Block, MiniBatch

_STAGE_CHUNK = 16384
# host-side staging temp budget: the chunked get_full_neighbor sweep
# allocates [chunk, cap] padded arrays — on power-law graphs cap is the
# hub degree, so the chunk length adapts to keep the temp bounded
_STAGE_TEMP_BYTES = 64 << 20
_U32_MAX = np.uint32(0xFFFFFFFF)


def _node_table(graph):
    """(ids u64, weights f64, types i32) for every node, shard-major —
    the same row order as Graph.lookup_rows. Local shards read their
    columns directly; remote shards sweep the `ids_by_rows` verb in row
    chunks (deterministic → served by the client ReadCache on repeats).
    """
    shards = graph.shards
    if all(
        hasattr(s, "node_ids") and hasattr(s, "node_weights")
        for s in shards
    ):
        return (
            np.concatenate([np.asarray(s.node_ids) for s in shards]),
            np.concatenate(
                [np.asarray(s.node_weights, np.float64) for s in shards]
            ),
            np.concatenate(
                [np.asarray(s.node_types, np.int32) for s in shards]
            ),
        )
    ids_p, wn_p, nt_p = [], [], []
    for sh in shards:
        n = int(sh.num_nodes)
        for lo in range(0, n, _STAGE_CHUNK):
            rows = np.arange(lo, min(lo + _STAGE_CHUNK, n), dtype=np.int64)
            try:
                i, w, t = sh.ids_by_rows(rows)
            except RuntimeError as e:
                if "unknown op" in str(e):
                    raise ValueError(
                        "remote device staging needs servers speaking the "
                        "ids_by_rows verb — upgrade the shard servers or "
                        "keep the host flows"
                    ) from e
                raise
            ids_p.append(np.asarray(i, np.uint64))
            wn_p.append(np.asarray(w, np.float64))
            nt_p.append(np.asarray(t, np.int32))
    if not ids_p:
        return (
            np.empty(0, np.uint64),
            np.empty(0, np.float64),
            np.empty(0, np.int32),
        )
    return np.concatenate(ids_p), np.concatenate(wn_p), np.concatenate(nt_p)


def _quantize_rows(wblock: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Per-row uint32-quantized CDF over the compacted weight block —
    the ONE quantization both layouts stage, so their draws invert
    identical integers. Exact f64 cumsum per row; invalid slots and
    zero-total rows fill 0xFFFFFFFF (never drawn below r == MAX, which
    the callers' deg-1 clamp absorbs)."""
    cum = np.cumsum(
        np.where(valid, wblock, 0.0).astype(np.float64), axis=1
    )
    total = cum[:, -1:]
    safe = np.maximum(total, np.finfo(np.float64).tiny)
    q = np.floor(cum / safe * np.float64(2**32 - 1))
    q = q.astype(np.uint64).astype(np.uint32)
    return np.where(valid & (total > 0), q, _U32_MAX)


def _segment_arange(counts: np.ndarray) -> np.ndarray:
    """[0..c0), [0..c1), ... concatenated (vectorized per-segment iota)."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64)
    ends = np.cumsum(counts)
    return np.arange(total) - np.repeat(ends - counts, counts)


# ---------------------------------------------------------------------------
# analytics frontier staging (euler_tpu/analytics)
# ---------------------------------------------------------------------------
# The whole-graph engine keeps per-shard dense f64 vertex state; staging
# it in HBM needs jax's x64 mode, which this repo leaves OFF globally
# (conftest runs f32). The scoped enable_x64 context preserves f64 end
# to end, so the device path's gathers and elementwise multiplies are
# IEEE-exact twins of the numpy host path — the order-sensitive segment
# reductions stay on the host in primitives.reduce_messages either way.


def _x64():
    try:
        from jax.experimental import enable_x64

        return enable_x64
    except ImportError:  # pragma: no cover - very old jax
        return None


def stage_frontier(values: np.ndarray):
    """Put one frontier shard's f64 state on device; host array when
    x64 staging is unavailable (callers stay correct either way)."""
    ctx = _x64()
    values = np.ascontiguousarray(values, np.float64)
    if ctx is None:
        return values
    with ctx():
        arr = jax.device_put(values)
    if arr.dtype != jnp.float64:  # x64 unavailable on this backend
        return values
    return arr


def frontier_contrib(weights, global_vec, src_rows):
    """Per-edge w[e] * frontier[src[e]] on device (f64 gather + multiply
    — elementwise IEEE ops, bit-identical to the numpy host path).
    Returns a host f64 array, or None when x64 staging is unavailable
    (the caller then runs the numpy path)."""
    ctx = _x64()
    if ctx is None:
        return None
    with ctx():
        vec = jnp.asarray(np.asarray(global_vec, np.float64))
        w = jnp.asarray(np.asarray(weights, np.float64))
        if vec.dtype != jnp.float64 or w.dtype != jnp.float64:
            return None
        out = w * jnp.take(
            vec, jnp.asarray(np.asarray(src_rows, np.int64)), axis=0
        )
        host = np.asarray(out, np.float64)
    return host


class DeviceGraphTables:
    """HBM-resident graph tables + traced draw primitives.

    Stages (once, host-side) the padded adjacency, degree vector, raw
    edge-weight rows (weighted graphs only — the per-row CDF is a cumsum
    on the gathered rows at draw time), a quantized node-weight CDF
    (non-uniform node weights only), and the id↔row maps. Subclasses
    compose `_draw_roots` / `_draw_neighbors` into batch shapes; all
    draws are jit-traceable.
    """

    is_device_flow = True

    def __call__(self):
        raise TypeError(
            f"{type(self).__name__} is not a host batch_fn; pass it to an "
            "Estimator (detected via is_device_flow) or call .sample(key) "
            "inside jit"
        )

    @staticmethod
    def _quantize_cdf(weights, what: str):
        """f64 weights → device uint32 CDF (exact adjacent values where a
        f32 cumsum over millions of entries would swallow small weights);
        raises on an empty or zero-total distribution."""
        cum = np.cumsum(np.asarray(weights, dtype=np.float64))
        if cum.size == 0 or cum[-1] <= 0:
            raise ValueError(f"{what} weights sum to zero")
        return jax.device_put(
            np.floor(cum / cum[-1] * np.float64(2**32 - 1)).astype(np.uint32)
        )

    def _stage_flat_edges(self, graph, edge_type: int = -1,
                          stage_er: bool = False):
        """Stage the flat (src, [type,] dst) edge columns + a weight CDF —
        the right layout for whole-edge draws on any degree distribution
        (8-12 bytes/edge, one searchsorted per draw, no max_degree
        guard). Edges with endpoints absent from the node table are
        dropped (the padded-adjacency path collapsed them to masked
        padding; flat staging must not emit them as real samples). Sets
        eh/et (int32, host id-truncation parity), er when stage_er (KG
        relations; LINE never reads it), num_edges, and edge_cdf (None
        when weights are uniform)."""
        if not all(hasattr(s, "edge_src") for s in graph.shards):
            raise ValueError(
                "flat edge staging needs local shards with edge columns "
                "(remote graphs keep the host batch sources)"
            )
        h = np.concatenate([np.asarray(s.edge_src) for s in graph.shards])
        t = np.concatenate([np.asarray(s.edge_dst) for s in graph.shards])
        r = np.concatenate([np.asarray(s.edge_types) for s in graph.shards])
        w = np.concatenate(
            [np.asarray(s.edge_weights, np.float64) for s in graph.shards]
        )
        rows_ht = graph.lookup_rows(np.concatenate([h, t]))
        keep = (rows_ht[: len(h)] >= 0) & (rows_ht[len(h) :] >= 0)
        if edge_type >= 0:
            keep &= r == edge_type
        h, t, r, w = h[keep], t[keep], r[keep], w[keep]
        if len(h) == 0 or np.sum(w) <= 0:
            # host sample_edge parity: empty or all-zero-weight edge
            # sets are unsampleable even when the weights are all equal
            raise ValueError("graph has no sampleable edges")
        to32 = lambda x: x.astype(np.int64).astype(np.int32)  # noqa: E731
        self.eh = jax.device_put(to32(h))
        self.et = jax.device_put(to32(t))
        self.er = jax.device_put(r.astype(np.int32)) if stage_er else None
        self.num_edges = len(h)
        self.edge_cdf = (
            None if np.all(w == w[0]) else self._quantize_cdf(w, "edge")
        )

    def _draw_edges(self, key, count: int):
        """[count] indices into the staged flat edge list, ∝ weight."""
        if self.edge_cdf is not None:
            rb = jax.random.bits(key, (count,), dtype=jnp.uint32)
            return jnp.minimum(
                jnp.searchsorted(self.edge_cdf, rb, side="right"),
                self.num_edges - 1,
            )
        return jax.random.randint(key, (count,), 0, self.num_edges)

    # SAGE-family tables draw only through _draw_neighbors and may stage
    # paged; flows that read the dense planes directly (walk bias,
    # per-relation type planes, layerwise scatter) override this False
    _PAGED_OK = True

    def __init__(
        self,
        graph,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
        stage_types: bool = False,
        layout: str = "auto",
        page_size: int = 16,
    ):
        """roots_pool: optional node ids to sample roots from (e.g. a
        train split); root_node_type restricts root draws to one node
        type instead (host sample_node(node_type) parity; ignored when a
        pool is given); default is every node. Root draws are proportional
        to node weights either way (uniform when weights are constant —
        host sample_node parity). max_degree is a guard on the DENSE
        staged adjacency width ((N+1)·Dmax·4 bytes of HBM): construction
        raises when the graph's true max degree exceeds it — truncation
        would bias sampling, so it is never done silently.

        layout: "dense" | "paged" | "auto". "auto" (default) picks dense
        while the max degree fits `max_degree` and otherwise stages the
        ragged paged layout (HBM ∝ edges; hub nodes span multiple
        fixed-size pages), so power-law graphs train on the device lane
        instead of raising. page_size must divide 128 (one page per DMA
        lane row). Paged and dense draws are bit-identical under the
        same keys (shared quantized-CDF inversion).

        mesh: a jax.sharding.Mesh for data-parallel training — sampled
        batch leaves are sharding-constrained along the mesh's data axis
        (each device materializes only its own batch slice; the staged
        tables replicate), so one traced sample() drives every device.
        Values are identical to the unsharded program for the same key.
        """
        self.mesh = mesh
        local = all(
            hasattr(s, "node_ids") and hasattr(s, "node_weights")
            for s in graph.shards
        )
        if not local and not all(
            hasattr(s, "call") for s in graph.shards
        ):
            raise ValueError(
                "device flows stage the adjacency host-side and need "
                "local shards or remote shards (wire staging)"
            )
        ids, wn, nt = _node_table(graph)
        # kept host-side for refresh_rows: the published-mutation restage
        # resolves global rows back to ids and re-fetches their adjacency
        self._ids_host = ids
        self._edge_types = (
            None if edge_types is None else [int(t) for t in edge_types]
        )
        self._stage_adjacency(
            graph, ids, edge_types, max_degree, stage_types,
            layout=layout, page_size=page_size,
        )
        self._stage_nodes(graph, ids, wn, nt, roots_pool, root_node_type)

    def _stage_degrees(self, graph, ids, edge_types) -> np.ndarray:
        """Per-node total degree, swept in chunks (one bounded RPC per
        chunk on remote graphs; degree_sum is ReadCache-deterministic)."""
        degs = np.zeros(len(ids), np.int64)
        for lo in range(0, len(ids), _STAGE_CHUNK):
            sub = ids[lo : lo + _STAGE_CHUNK]
            degs[lo : lo + len(sub)] = graph.degree_sum(sub, edge_types)
        return degs

    def _stage_adjacency(
        self,
        graph,
        ids,
        edge_types,
        max_degree: int,
        stage_types: bool,
        layout: str = "auto",
        page_size: int = 16,
    ):
        if layout not in ("auto", "dense", "paged"):
            raise ValueError(f"unknown layout {layout!r}")
        degs = self._stage_degrees(graph, ids, edge_types)
        dmax = max(int(degs.max(initial=0)), 1)
        paged_ok = self._PAGED_OK and not stage_types
        if layout == "auto":
            layout = "paged" if (dmax > max_degree and paged_ok) else "dense"
        if layout == "paged" and not paged_ok:
            raise ValueError(
                f"{type(self).__name__} reads the dense adjacency planes "
                "directly (bias/type/layerwise math) — the paged layout "
                "serves the SAGE-family flows only"
            )
        if layout == "dense" and dmax > max_degree:
            raise ValueError(
                f"graph max degree {dmax} exceeds max_degree={max_degree}; "
                f"the dense staged adjacency would cost (N+1)*{dmax}*4 "
                "bytes — use the paged device lane instead "
                "(layout='paged', or layout='auto' which selects it "
                "automatically: fixed-size neighbor pages, HBM ∝ edges), "
                "or raise the cap explicitly after the memory math"
            )
        self.layout = layout
        if layout == "paged":
            self._stage_paged(graph, ids, degs, edge_types, page_size)
            return
        n = len(ids)
        adj = np.zeros((n + 1, dmax), dtype=np.int32)
        deg = np.zeros(n + 1, dtype=np.int32)
        wtab = np.zeros((n + 1, dmax), dtype=np.float32)
        ttab = (
            np.full((n + 1, dmax), -1, dtype=np.int32) if stage_types else None
        )
        unit_w = True
        for lo in range(0, n, _STAGE_CHUNK):
            sub = ids[lo : lo + _STAGE_CHUNK]
            nbr, w, tt, mask, _ = graph.get_full_neighbor(
                sub, edge_types, max_degree=dmax
            )
            unit_w = unit_w and bool(np.all(w[mask] == 1.0))
            rows = graph.lookup_rows(nbr.ravel()).reshape(nbr.shape)
            # row+1 encoding, 0 = padding (matches DeviceFeatureCache's
            # zero row); masked or unknown neighbors collapse to padding
            block = np.where(mask & (rows >= 0), rows + 1, 0).astype(np.int32)
            # compact valid entries to the front so idx < deg hits them
            order = np.argsort(block == 0, axis=1, kind="stable")
            sl = slice(1 + lo, 1 + lo + len(sub))
            adj[sl, : block.shape[1]] = np.take_along_axis(block, order, axis=1)
            wtab[sl, : block.shape[1]] = np.take_along_axis(
                np.where(block > 0, w, 0.0).astype(np.float32), order, axis=1
            )
            if ttab is not None:  # edge types of each slot (KG relations)
                ttab[sl, : block.shape[1]] = np.take_along_axis(
                    np.where(block > 0, tt, -1).astype(np.int32), order, axis=1
                )
            deg[sl] = (block > 0).sum(axis=1)
        # a positive-degree row whose weights are all zero is unsampleable
        # (host _WeightedSampler semantics: zero total → padding)
        # per-node out-strength (edge-weight row sums): zero-strength rows
        # are unsampleable, and DeviceGaeFlow draws edge sources ∝ it
        strength = wtab.sum(axis=1, dtype=np.float64)
        deg[strength <= 0.0] = 0
        self._out_strength = strength
        self.adj = jax.device_put(adj)
        self.deg = jax.device_put(deg)
        self.unit_w = unit_w
        # weighted graphs stage the RAW weight rows (exact values for
        # edge_w and bias math) plus the per-row quantized CDF — the ONE
        # inversion table shared bit-for-bit with the paged layout
        # (trailing f64 cumsum at staging; device keeps uint32)
        self.wtab = None if unit_w else jax.device_put(wtab)
        if unit_w:
            self.qtab = None
        else:
            valid = (
                np.arange(dmax)[None, :] < deg[:, None]
            )
            self.qtab = jax.device_put(_quantize_rows(wtab, valid))
        self.ttab = jax.device_put(ttab) if ttab is not None else None
        self.max_deg = dmax

    def _stage_paged(self, graph, ids, degs, edge_types, page_size: int):
        """Ragged paged staging: compacted neighbor entries (same order
        as the dense compaction, so draws land on the same slots) packed
        into fixed-size pages in one flat buffer; per-node page table in
        `page_start`. HBM ∝ edges — no max_degree failure mode."""
        from euler_tpu.distributed.codec import page_dtype
        from euler_tpu.ops.pallas_kernels import (
            PAGE_LANES,
            _as_lane_rows,
            pack_bf16_words,
        )

        P = int(page_size)
        if P <= 0 or PAGE_LANES % P:
            raise ValueError(
                f"page_size must divide {PAGE_LANES} (one page per DMA "
                f"lane row); got {P}"
            )
        n = len(ids)
        deg = np.zeros(n + 1, dtype=np.int32)
        strength = np.zeros(n + 1, dtype=np.float64)
        unit_w = True
        vals_p, w_p, q_p = [], [], []
        lo = 0
        while lo < n:
            # temp budget: [chunk, cap] padded host arrays per sweep step
            cap_hint = max(int(degs[lo : lo + _STAGE_CHUNK].max(initial=1)), 1)
            chunk = max(
                256, min(_STAGE_CHUNK, _STAGE_TEMP_BYTES // (cap_hint * 8))
            )
            sub = ids[lo : lo + chunk]
            cap = max(int(degs[lo : lo + len(sub)].max(initial=0)), 1)
            nbr, w, _, mask, _ = graph.get_full_neighbor(
                sub, edge_types, max_degree=cap
            )
            unit_w = unit_w and bool(np.all(w[mask] == 1.0))
            rows = graph.lookup_rows(nbr.ravel()).reshape(nbr.shape)
            blk0 = np.where(mask & (rows >= 0), rows + 1, 0).astype(np.int32)
            order = np.argsort(blk0 == 0, axis=1, kind="stable")
            block = np.take_along_axis(blk0, order, axis=1)
            wblk = np.take_along_axis(
                np.where(blk0 > 0, w, 0.0).astype(np.float32), order, axis=1
            )
            d = (block > 0).sum(axis=1).astype(np.int32)
            st = wblk.sum(axis=1, dtype=np.float64)
            d[st <= 0.0] = 0  # zero-strength rows are unsampleable
            sl = slice(1 + lo, 1 + lo + len(sub))
            deg[sl] = d
            strength[sl] = st
            valid = np.arange(block.shape[1])[None, :] < d[:, None]
            vals_p.append(block[valid])
            w_p.append(wblk[valid])
            q_p.append(_quantize_rows(wblk, valid)[valid])
            lo += len(sub)
        self._out_strength = strength
        npages = -(-deg.astype(np.int64) // P)  # ceil(deg/P); 0 for deg 0
        ps = np.zeros(n + 2, dtype=np.int64)
        ps[1:] = np.cumsum(npages)
        total_pages = max(int(ps[-1]), 1)
        flat = np.zeros(total_pages * P, dtype=np.int32)
        flat_w = np.zeros(total_pages * P, dtype=np.float32)
        flat_q = np.full(total_pages * P, _U32_MAX, dtype=np.uint32)
        # entries of node r (row+1 space) land at ps[r]*P + [0, deg_r)
        dest = np.repeat(ps[:-1] * P, deg) + _segment_arange(deg)
        if len(dest):
            flat[dest] = np.concatenate(vals_p)
            flat_w[dest] = np.concatenate(w_p)
            flat_q[dest] = np.concatenate(q_p)
        self.pages2d = _as_lane_rows(jnp.asarray(flat))
        self._ps_host = ps  # page table, host copy (refresh_rows spans)
        self.page_start = jax.device_put(ps.astype(np.int32))
        self.deg = jax.device_put(deg)
        self.unit_w = unit_w
        # EULER_TPU_PAGE_DTYPE=bf16 packs the weight plane two-bf16-per-
        # u32 (half the HBM + DMA bytes) and dequantizes inside the
        # gather. Emitted batches already ship bf16 edge weights, and
        # bf16(bf16(x)) == bf16(x), so packed draws stay BIT-IDENTICAL
        # to the f32 plane — this lane spends no accuracy budget. Odd
        # page sizes would let a row's page span straddle a packed word
        # at refresh time, so P=1 stays unpacked.
        self._page_w_packed = (
            not unit_w and page_dtype() == "bf16" and P % 2 == 0
        )
        if unit_w:
            self.page_w2d = self.page_q2d = self.page_bound = None
        else:
            self.page_w2d = _as_lane_rows(
                pack_bf16_words(flat_w)
                if self._page_w_packed
                else jnp.asarray(flat_w)
            )
            self.page_q2d = _as_lane_rows(jnp.asarray(flat_q))
            # per-page boundary = the page's last valid quantized-CDF
            # value (pads are U32_MAX, and a node's final page ends at
            # U32_MAX anyway, so a plain per-page max is exact)
            self.page_bound = jax.device_put(
                flat_q.reshape(total_pages, P).max(axis=1)
            )
        self.page_size = P
        # clamp caps for masked draws: a trailing degree-0 node's
        # page_start equals total_pages, and its (deg>0-masked) gather
        # index must still stay inside the buffers — XLA clips gathers,
        # but the kernel DMAs must never be handed an OOB row
        self._page_cap = total_pages - 1
        self._slot_cap = total_pages * P - 1
        self.max_pages = int(npages.max(initial=0))
        # binary-search depth over a node's page range (static at trace)
        self._search_iters = max(1, int(self.max_pages).bit_length() + 1)
        self.max_deg = max(int(deg.max(initial=0)), 1)
        # dense planes absent on purpose: flows that need them are gated
        # by _PAGED_OK at staging time
        self.adj = self.wtab = self.qtab = self.ttab = None

    # -- published-mutation restage --------------------------------------

    def refresh_rows(self, graph, rows) -> int:
        """Re-stage ONLY the given GLOBAL node rows after a published
        graph mutation (feed it ``GraphWriter.publish()["rows"]``) — the
        adjacency twin of ``DeviceFeatureCache.refresh_rows``. Dense
        layout patches the touched ``[row]`` slices of the adj/deg/
        weight planes; paged layout re-packs only the ⌈deg/P⌉ pages of
        the mutated rows (page-granular, the Ragged-Paged-Attention
        indirection shape). Structural changes a patch cannot express —
        node count changed, a degree outgrowing its staged capacity, or
        a unit-weight staging turning weighted — raise ValueError: build
        a fresh flow for those. Post-restage draws are bit-identical to
        a from-scratch staging of the merged graph under the same key
        (pinned by tests/test_delta.py). Returns rows re-staged."""
        rows = np.unique(np.asarray(rows, dtype=np.int64).reshape(-1))
        rows = rows[rows >= 0]
        if not len(rows):
            return 0
        total = int(sum(int(s.num_nodes) for s in graph.shards))
        if total != self.num_nodes:
            raise ValueError(
                f"node count changed ({self.num_nodes} staged, {total} "
                "now) — a row patch cannot re-shape the staged tables; "
                "build a fresh device flow"
            )
        if int(rows.max()) >= self.num_nodes:
            raise ValueError("refresh_rows: row out of range")
        ids = self._ids_host[rows]
        degs = np.asarray(
            graph.degree_sum(ids, self._edge_types), np.int64
        )
        if self.layout == "paged":
            return self._refresh_paged(graph, rows, ids, degs)
        return self._refresh_dense(graph, rows, ids, degs)

    def _refresh_block(self, graph, ids, cap: int):
        """Chunk of the staging sweep for a row subset: compacted
        neighbor block + weights + degree + strength, the exact shapes
        `_stage_adjacency`/`_stage_paged` put in the tables."""
        nbr, w, tt, mask, _ = graph.get_full_neighbor(
            ids, self._edge_types, max_degree=cap
        )
        rws = graph.lookup_rows(nbr.ravel()).reshape(nbr.shape)
        blk0 = np.where(mask & (rws >= 0), rws + 1, 0).astype(np.int32)
        order = np.argsort(blk0 == 0, axis=1, kind="stable")
        block = np.take_along_axis(blk0, order, axis=1)
        wblk = np.take_along_axis(
            np.where(blk0 > 0, w, 0.0).astype(np.float32), order, axis=1
        )
        ttb = np.take_along_axis(
            np.where(blk0 > 0, tt, -1).astype(np.int32), order, axis=1
        )
        d = (block > 0).sum(axis=1).astype(np.int32)
        st = wblk.sum(axis=1, dtype=np.float64)
        d[st <= 0.0] = 0
        unit = bool(np.all(w[mask] == 1.0)) if mask.any() else True
        if self.unit_w and not unit:
            raise ValueError(
                "mutation introduced non-unit edge weights on a "
                "unit-weight staging — build a fresh device flow"
            )
        return block, wblk, ttb, d, st

    def _refresh_dense(self, graph, rows, ids, degs) -> int:
        width = int(self.adj.shape[1])
        if int(degs.max(initial=0)) > width:
            raise ValueError(
                f"mutated degree {int(degs.max())} outgrew the staged "
                f"dense width {width} — build a fresh device flow (or "
                "the paged layout, which has no width to outgrow)"
            )
        block, wblk, ttb, d, st = self._refresh_block(graph, ids, width)
        r1 = rows + 1
        self.adj = self.adj.at[r1].set(jnp.asarray(block))
        self.deg = self.deg.at[r1].set(jnp.asarray(d))
        self._out_strength[r1] = st
        if self.ttab is not None:
            self.ttab = self.ttab.at[r1].set(jnp.asarray(ttb))
        if not self.unit_w:
            valid = np.arange(width)[None, :] < d[:, None]
            self.wtab = self.wtab.at[r1].set(jnp.asarray(wblk))
            self.qtab = self.qtab.at[r1].set(
                jnp.asarray(_quantize_rows(wblk, valid))
            )
        return len(rows)

    def _refresh_paged(self, graph, rows, ids, degs) -> int:
        P = self.page_size
        ps = self._ps_host
        r1 = rows + 1
        alloc = ps[r1 + 1] - ps[r1]  # pages staged for each row
        need = -(-degs // P)
        if np.any(need > alloc):
            over = rows[need > alloc][:4]
            raise ValueError(
                f"mutated degree outgrew the staged page allocation for "
                f"rows {over.tolist()} (⌈deg/{P}⌉ pages are fixed at "
                "staging) — build a fresh device flow"
            )
        cap = max(int(degs.max(initial=0)), 1)
        block, wblk, _, d, st = self._refresh_block(graph, ids, cap)
        # rewrite each row's WHOLE allocated span (stale tail slots and
        # pages become padding), so only ⌈deg/P⌉ pages per mutated row
        # are touched and untouched rows' pages never move
        spans = (alloc * P).astype(np.int64)
        total = int(spans.sum())
        vals = np.zeros(total, np.int32)
        wv = np.zeros(total, np.float32)
        qv = np.full(total, _U32_MAX, dtype=np.uint32)
        dest = np.repeat(ps[r1] * P, spans) + _segment_arange(spans)
        src_rows = np.repeat(np.arange(len(rows)), spans)
        src_cols = _segment_arange(spans)
        put = src_cols < np.repeat(d.astype(np.int64), spans)
        sr, sc = src_rows[put], np.minimum(src_cols[put], block.shape[1] - 1)
        vals[put] = block[sr, sc]
        wv[put] = wblk[sr, sc]
        self.deg = self.deg.at[r1].set(jnp.asarray(d))
        self._out_strength[r1] = st
        lanes = int(self.pages2d.shape[1])
        self.pages2d = self.pages2d.at[dest // lanes, dest % lanes].set(
            jnp.asarray(vals)
        )
        if not self.unit_w:
            valid = np.arange(block.shape[1])[None, :] < d[:, None]
            q = _quantize_rows(wblk, valid)
            qv[put] = q[sr, sc]
            if getattr(self, "_page_w_packed", False):
                # every span is a whole-page run and P is even, so spans
                # start word-aligned with even length: pack the patch
                # values pairwise and rewrite whole u32 words — no
                # read-modify-write of half-covered words can occur
                from euler_tpu.ops.pallas_kernels import pack_bf16_words

                words = pack_bf16_words(wv)
                wdest = dest[0::2] // 2
                self.page_w2d = self.page_w2d.at[
                    wdest // lanes, wdest % lanes
                ].set(words)
            else:
                self.page_w2d = self.page_w2d.at[
                    dest // lanes, dest % lanes
                ].set(jnp.asarray(wv))
            self.page_q2d = self.page_q2d.at[
                dest // lanes, dest % lanes
            ].set(jnp.asarray(qv))
            touched_pages = np.repeat(ps[r1], alloc) + _segment_arange(
                alloc
            )
            self.page_bound = self.page_bound.at[touched_pages].set(
                jnp.asarray(qv.reshape(-1, P).max(axis=1))
            )
        return len(rows)

    @property
    def _kimpl(self) -> str:
        """Paged-kernel impl derived from the global pallas mode: 'off'
        rides the jitted jnp reference, 'interpret'/'pallas' are the
        explicit kernel forms, 'auto' defers to the kernels' own
        measured-boundary auto (currently the reference — see
        ops/PALLAS_BENCH.md)."""
        from euler_tpu.ops import pallas_mode

        mode = pallas_mode()
        if mode == "off":
            return "xla"
        if mode in ("interpret", "pallas"):
            return mode
        return "auto"

    def _stage_nodes(
        self, graph, ids, wn, nt, roots_pool, root_node_type: int
    ):
        n = len(ids)
        # weight-proportional root draws (host sample_node parity): a
        # uint32-quantized CDF, binary-searched on device — over all nodes,
        # or over roots_pool's members when a pool restricts the draw.
        # Integer quantization keeps adjacent cum values exact where f32
        # cumsum over >1e6 nodes would swallow small weights.
        wn = np.asarray(wn, dtype=np.float64)
        # global (unrestricted) node CDF — negative sampling draws from
        # ALL nodes even when roots are pool/type-restricted (host
        # unsupervised_batches neg_type=-1 parity)
        self.global_cdf = (
            self._quantize_cdf(wn, "graph node")
            if wn.size and not np.all(wn == wn[0])
            else None
        )
        pool_rows = None
        if roots_pool is not None:
            pool_rows = graph.lookup_rows(
                np.asarray(roots_pool, dtype=np.uint64)
            )
            if np.any(pool_rows < 0):
                raise ValueError("roots_pool contains unknown node ids")
            wn = wn[pool_rows]
        elif root_node_type >= 0:
            pool_rows = np.nonzero(
                np.asarray(nt) == root_node_type
            )[0].astype(np.int64)
            if not len(pool_rows):
                raise ValueError(
                    f"no nodes of type {root_node_type} to sample roots from"
                )
            wn = wn[pool_rows]
        self.node_cdf = (
            self._quantize_cdf(wn, "root node")
            if wn.size and not np.all(wn == wn[0])
            else None
        )
        # int32 view of the u64 id space (host flows apply the same
        # truncation); index 0 (padding) maps to -1
        node_id = np.full(n + 1, -1, dtype=np.int32)
        node_id[1:] = ids.astype(np.int64).astype(np.int32)
        self.node_id = jax.device_put(node_id)
        self.roots = (
            jax.device_put(pool_rows.astype(np.int32) + 1)
            if pool_rows is not None
            else None
        )
        self.num_nodes = n

    # -- traced draw primitives ------------------------------------------

    def _dp(self, x):
        """Constrain a batch-leading array to the mesh's data axis (same
        divisibility rule as parallel.shard_batch); no-op without a mesh."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        from euler_tpu.parallel import DATA_AXIS

        nd = self.mesh.shape[DATA_AXIS]
        spec = P(DATA_AXIS) if x.ndim >= 1 and x.shape[0] % nd == 0 else P()
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec)
        )

    def _draw_roots(self, key, count: int):
        """[count] root draws in row+1 space, weight-proportional."""
        if self.node_cdf is not None:
            r = jax.random.bits(key, (count,), dtype=jnp.uint32)
            pick = jnp.searchsorted(self.node_cdf, r, side="right")
            pick = jnp.minimum(pick, len(self.node_cdf) - 1).astype(jnp.int32)
            return self.roots[pick] if self.roots is not None else pick + 1
        if self.roots is not None:
            pick = jax.random.randint(key, (count,), 0, len(self.roots))
            return self.roots[pick]
        return jax.random.randint(key, (count,), 1, self.num_nodes + 1)

    def _draw_global_nodes(self, key, count: int):
        """[count] draws over ALL nodes (ignores roots_pool/root_node_type)
        — the negative-sampling distribution (sample_node(-1) parity)."""
        if self.global_cdf is not None:
            r = jax.random.bits(key, (count,), dtype=jnp.uint32)
            pick = jnp.searchsorted(self.global_cdf, r, side="right")
            return jnp.minimum(pick, self.num_nodes - 1).astype(jnp.int32) + 1
        return jax.random.randint(key, (count,), 1, self.num_nodes + 1)

    def _draw_neighbors_typed(self, cur, key, k: int, rel: int):
        """[W] rows → per-RELATION draws: ([W·k] rows, [W·k] f32 weights,
        [W·k] valid mask). Requires stage_types=True. Weights are masked
        to slots of type `rel` before the CDF inversion — the same
        distribution as the host sample_neighbor(cur, [rel], k)."""
        width = cur.shape[0]
        nbr_rows = self.adj[cur]  # [W, D]
        w = (
            self.wtab[cur]
            if self.wtab is not None
            else (nbr_rows > 0).astype(jnp.float32)
        )
        w = w * (self.ttab[cur] == rel)
        cw = jnp.cumsum(w, axis=1)
        total = cw[:, -1]
        u = jax.random.uniform(key, (width, k)) * total[:, None]
        idx = (cw[:, None, :] <= u[:, :, None]).sum(axis=-1)
        idx = jnp.minimum(idx, self.adj.shape[1] - 1)
        # type-r support is NON-contiguous, so the u→1 f32 overshoot can
        # land on a wrong-relation or padded slot (w there is 0); redirect
        # those draws to the row's LAST in-support slot (the sibling
        # _draw_neighbors' deg-1 clamp, generalized to a masked row)
        wpick = jnp.take_along_axis(w, idx, axis=1)
        last = jnp.argmax(
            jnp.where(w > 0, jnp.arange(w.shape[1]), -1), axis=1
        )
        idx = jnp.where(wpick > 0, idx, last[:, None])
        alive = total > 0
        nbr = jnp.where(
            alive[:, None], jnp.take_along_axis(nbr_rows, idx, axis=1), 0
        )
        ew = jnp.where(
            alive[:, None], jnp.take_along_axis(w, idx, axis=1), 0.0
        )
        valid = (nbr > 0).reshape(-1)
        return nbr.reshape(-1), ew.reshape(-1), valid

    def _stage_edge_src_cdf(self):
        """Quantized CDF over per-node out-strength: drawing a source from
        it and then a neighbor within the row draws an edge ∝ weight
        (P(e) = strength(src)/W · w(e)/strength(src) = w(e)/W — the host
        sample_edge alias-table distribution)."""
        self.edge_src_cdf = self._quantize_cdf(
            self._out_strength[1:], "edge-source out-strength"
        )

    def _draw_edge_sources(self, key, count: int):
        """[count] edge-source rows (row+1 space) ∝ out-strength."""
        r = jax.random.bits(key, (count,), dtype=jnp.uint32)
        pick = jnp.searchsorted(self.edge_src_cdf, r, side="right")
        return jnp.minimum(pick, self.num_nodes - 1).astype(jnp.int32) + 1

    def _draw_neighbors(self, cur, key, k: int):
        """[W] rows → ([W·k] rows, [W·k] bf16 weights or None, [W, k] slot idx).

        Uniform graphs draw a slot index directly; weighted graphs invert
        the per-row uint32-quantized CDF staged at construction — the
        SAME integers in both layouts, so the paged lane below draws
        bit-identical neighbors under the same key. Padding rows (0)
        yield padding.
        """
        if getattr(self, "layout", "dense") == "paged":
            return self._draw_neighbors_paged(cur, key, k)
        width = cur.shape[0]
        deg = self.deg[cur]
        if self.unit_w:
            u = jax.random.uniform(key, (width, k))
            idx = (u * deg[:, None]).astype(jnp.int32)
            ew = None
        else:
            r = jax.random.bits(key, (width, k), dtype=jnp.uint32)
            qrow = self.qtab[cur]  # [W, D] uint32 per-row CDF
            idx = (
                (qrow[:, None, :] <= r[:, :, None])
                .sum(axis=-1)
                .astype(jnp.int32)
            )
        idx = jnp.minimum(idx, jnp.maximum(deg[:, None] - 1, 0))
        nbr = jnp.where(
            deg[:, None] > 0, self.adj[cur[:, None], idx], 0
        ).reshape(-1)
        if not self.unit_w:
            # exact staged weight of the drawn edge (zero on padded slots)
            ew = (
                jnp.take_along_axis(self.wtab[cur], idx, axis=1)
                .reshape(-1)
                .astype(jnp.bfloat16)
            )
        return nbr, ew, idx

    def _draw_neighbors_paged(self, cur, key, k: int):
        """Paged twin of _draw_neighbors: two-level quantized-CDF
        inversion (page-boundary binary search + in-page count) and
        neighbor/weight gathers through the page indirection — identical
        integers to the dense inversion, different memory layout. The
        page reads route through ops/pallas_kernels entry points."""
        from euler_tpu.ops.pallas_kernels import (
            paged_cdf_count,
            paged_gather,
            paged_gather_dequant,
            paged_page_search,
        )

        width = cur.shape[0]
        deg = self.deg[cur]
        ps = self.page_start[cur]
        P = self.page_size
        impl = self._kimpl
        if self.unit_w:
            u = jax.random.uniform(key, (width, k))
            idx = (u * deg[:, None]).astype(jnp.int32)
            ew = None
        else:
            r = jax.random.bits(key, (width, k), dtype=jnp.uint32)
            npages = self.page_start[cur + 1] - ps
            pg = paged_page_search(
                self.page_bound, ps, npages, r, self._search_iters
            )
            pgc = jnp.minimum(pg, jnp.maximum(npages[:, None] - 1, 0))
            page = jnp.minimum(ps[:, None] + pgc, self._page_cap)
            cnt = paged_cdf_count(self.page_q2d, page, r, P, impl=impl)
            idx = pgc * P + cnt
        idx = jnp.minimum(idx, jnp.maximum(deg[:, None] - 1, 0))
        fidx = jnp.minimum(ps[:, None] * P + idx, self._slot_cap)
        nbr = jnp.where(
            deg[:, None] > 0,
            paged_gather(self.pages2d, fidx, impl=impl),
            0,
        ).reshape(-1)
        if not self.unit_w:
            # packed plane: bf16 dequantized AT the gather (half the
            # DMA bytes); the trailing bf16 cast below makes the packed
            # and f32 planes emit bit-identical weights either way
            wvals = (
                paged_gather_dequant(self.page_w2d, fidx, impl=impl)
                if getattr(self, "_page_w_packed", False)
                else paged_gather(self.page_w2d, fidx, impl=impl)
            )
            ew = (
                jnp.where(deg[:, None] > 0, wvals, 0.0)
                .reshape(-1)
                .astype(jnp.bfloat16)
            )
        return nbr, ew, idx


class DeviceSageFlow(DeviceGraphTables):
    """HBM-resident adjacency + traced fanout sampling → lean MiniBatch.

    Pass an instance as an Estimator's `batch_fn`: the Estimator detects
    `is_device_flow` and generates batches inside the jitted train step
    from per-step PRNG keys (estimator.py `_train_step_scan`). The batch
    pytree is identical to what a lean host `SageDataFlow` ships after
    device_put, so models, hydration, and the feature cache are shared.
    """

    def __init__(
        self,
        graph,
        fanouts,
        batch_size: int,
        label_feature: str | None = None,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
        with_hop_ids: bool = False,
        layout: str = "auto",
        page_size: int = 16,
    ):
        """with_hop_ids=True ships per-hop int32 node ids in the batch —
        what id-embedding models (ShallowEncoder with max_id) consume.
        The host LEAN wire must omit hop_ids (they cost wire bytes); on
        device they are a free node_id gather, so id-embedding models
        run through the device flow at no extra cost.

        layout="auto" stages the dense padded adjacency while the max
        degree fits `max_degree` and the ragged paged layout otherwise
        (power-law graphs; HBM ∝ edges) — draws are bit-identical either
        way under the same keys."""
        super().__init__(
            graph, edge_types, max_degree, roots_pool, root_node_type, mesh,
            layout=layout, page_size=page_size,
        )
        self.fanouts = [int(k) for k in fanouts]
        self.batch_size = int(batch_size)
        self.with_hop_ids = bool(with_hop_ids)
        if label_feature is not None:
            from euler_tpu.estimator.feature_cache import DeviceFeatureCache

            self.label_table = DeviceFeatureCache(graph, [label_feature]).table
        else:
            self.label_table = None

    def _fanout_batch(self, roots, key) -> MiniBatch:
        """Traced multi-hop fanout from [B] root rows → lean MiniBatch."""
        cur = self._dp(roots)
        feats = [cur]
        blocks = []
        width = roots.shape[0]
        for k, hk in zip(self.fanouts, jax.random.split(key, len(self.fanouts))):
            nbr, ew, _ = self._draw_neighbors(cur, hk, k)
            nbr = self._dp(nbr)
            if ew is not None:
                # weighted-lean wire parity: bf16 weights ride the batch
                ew = self._dp(ew)
            blocks.append(
                Block(
                    edge_src=None, edge_dst=None, edge_w=ew, mask=None,
                    n_src=width * k, n_dst=width, grid=k,
                )
            )
            feats.append(nbr)
            cur = nbr
            width *= k
        labels = (
            self.label_table[feats[0]] if self.label_table is not None else None
        )
        if labels is not None:
            labels = self._dp(labels)
        return MiniBatch(
            feats=tuple(feats),
            masks=None,
            blocks=tuple(blocks),
            root_idx=self._dp(self.node_id[feats[0]]),
            labels=labels,
            # pad rows map to id -1 (host non-lean parity); the encoder
            # clips them to 0, but hydrate_blocks derives hop masks from
            # the rows-mode feats before the model applies, so pad-slot
            # embeddings never reach the aggregation
            hop_ids=(
                tuple(self._dp(self.node_id[f]) for f in feats)
                if self.with_hop_ids
                else None
            ),
        )

    def sample(self, key) -> MiniBatch:
        """key → lean MiniBatch, jit-traceable (call inside the train step)."""
        kroot, khops = jax.random.split(key)
        return self._fanout_batch(
            self._draw_roots(kroot, self.batch_size), khops
        )



class DeviceUnsupSageFlow(DeviceSageFlow):
    """On-device (src, pos, negs) fanout triples for GraphSAGEUnsupervised.

    Host parity: estimator.unsupervised_batches — pos is a sampled 1-hop
    neighbor of src (falling back to src itself when src has none), negs
    are globally drawn nodes; each of the three gets its own multi-hop
    lean fanout batch. sample(key) returns the 3-tuple of MiniBatches the
    model's (src, pos, negs) signature consumes.
    """

    def __init__(
        self,
        graph,
        fanouts,
        batch_size: int,
        num_negs: int = 5,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
        with_hop_ids: bool = False,
        layout: str = "auto",
        page_size: int = 16,
    ):
        super().__init__(
            graph, fanouts, batch_size, None, edge_types, max_degree,
            roots_pool, root_node_type, mesh, with_hop_ids=with_hop_ids,
            layout=layout, page_size=page_size,
        )
        self.num_negs = int(num_negs)

    def sample(self, key) -> tuple:
        kroot, kpos, kneg, ks, kp, kn = jax.random.split(key, 6)
        src = self._draw_roots(kroot, self.batch_size)
        nbr, _, _ = self._draw_neighbors(src, kpos, 1)
        pos = jnp.where(nbr > 0, nbr, src)
        negs = self._draw_global_nodes(kneg, self.batch_size * self.num_negs)
        return (
            self._fanout_batch(src, ks),
            self._fanout_batch(pos, kp),
            self._fanout_batch(negs, kn),
        )


class DeviceWalkFlow(DeviceGraphTables):
    """On-device random walks + skip-gram pairs for DeepWalk/node2vec.

    Replaces the host walk pipeline (graph.random_walk → dataflow.walk
    gen_pair → negative draws, models/embedding_models.deepwalk_batches)
    with traced XLA ops: the walk is a length-L chain of single-neighbor
    draws against the HBM adjacency, the sliding-window pair extraction
    is a static column gather, and negatives ride the same node CDF.
    `sample(key)` returns the exact dict batch `SkipGramModel` consumes
    (src/pos int32 ids, negs [P, num_negs], mask) with identical padding
    semantics (-1 ids on dead-walk slots are excluded by the mask).

    node2vec bias (p/q ≠ 1, random_walk_op.cc:27-90): each step biases
    the current node's weight row by 1/p toward the previous node, 1 for
    neighbors of the previous node, 1/q elsewhere — the membership test
    is a [W, D, D] compare against prev's adjacency row, so the biased
    path is gated to max degree ≤ 64 (guarded at construction).
    """

    _PAGED_OK = False  # _walk_step reads the dense adj plane directly

    def __init__(
        self,
        graph,
        batch_size: int,
        walk_len: int = 5,
        window: int = 2,
        num_negs: int = 5,
        p: float = 1.0,
        q: float = 1.0,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
        layout: str = "auto",
    ):
        super().__init__(
            graph, edge_types, max_degree, roots_pool, root_node_type, mesh,
            layout=layout,
        )
        self.batch_size = int(batch_size)
        self.walk_len = int(walk_len)
        self.num_negs = int(num_negs)
        self.p, self.q = float(p), float(q)
        self.biased = not (p == 1.0 and q == 1.0)
        if self.biased and self.max_deg > 64:
            raise ValueError(
                f"node2vec bias needs a [W, D, D] membership test; max "
                f"degree {self.max_deg} > 64 makes that table too wide — "
                "use the host random_walk for this graph"
            )
        # static sliding-window column indices (walk.py gen_pair parity):
        # for each offset, source columns [lo, hi) pair with context
        # columns [lo+off, hi+off); padded tail slots point at a dead
        # column marked invalid
        length = self.walk_len + 1
        src_cols, ctx_cols, valid = [], [], []
        for off in range(-window, window + 1):
            if off == 0:
                continue
            lo, hi = max(0, -off), min(length, length - off)
            cols = np.arange(length)
            s = np.where(cols < hi - lo, cols + lo, 0)
            c = np.where(cols < hi - lo, cols + lo + off, 0)
            src_cols.append(s)
            ctx_cols.append(c)
            valid.append(cols < hi - lo)
        self._src_cols = np.concatenate(src_cols)
        self._ctx_cols = np.concatenate(ctx_cols)
        self._col_valid = np.concatenate(valid)
        self.pairs_per_walk = len(self._src_cols)

    def _walk_step(self, cur, prev, key):
        """One biased transition (p/q): weight row × node2vec bias, then
        the same inverse-CDF draw as the unbiased path."""
        width = cur.shape[0]
        nbr_rows = self.adj[cur]  # [W, D]
        deg = self.deg[cur]
        if self.unit_w:
            w = (nbr_rows > 0).astype(jnp.float32)
        else:
            w = self.wtab[cur]
        # bias: 1/p back to prev, 1 if adjacent to prev, 1/q otherwise
        prev_nbrs = self.adj[prev]  # [W, D]
        is_back = nbr_rows == prev[:, None]
        near = (
            (nbr_rows[:, :, None] == prev_nbrs[:, None, :])
            & (prev_nbrs[:, None, :] > 0)
        ).any(axis=-1)
        bias = jnp.where(
            is_back, 1.0 / self.p, jnp.where(near, 1.0, 1.0 / self.q)
        )
        bias = jnp.where((prev > 0)[:, None], bias, 1.0)
        bw = w * bias * (nbr_rows > 0)
        cum = jnp.cumsum(bw, axis=1)
        u = jax.random.uniform(key, (width, 1)) * cum[:, -1][:, None]
        idx = (cum <= u).sum(axis=1)
        idx = jnp.minimum(idx, jnp.maximum(deg - 1, 0))
        alive = (deg > 0) & (cum[:, -1] > 0)
        return jnp.where(alive, nbr_rows[jnp.arange(width), idx], 0)

    def sample(self, key) -> dict:
        """key → SkipGramModel batch dict, jit-traceable."""
        kroot, kneg, kwalk = jax.random.split(key, 3)
        cur = self._dp(self._draw_roots(kroot, self.batch_size))
        walk = [cur]
        prev = jnp.zeros_like(cur)
        for sk in jax.random.split(kwalk, self.walk_len):
            if self.biased:
                nxt = self._walk_step(cur, prev, sk)
            else:
                nxt, _, _ = self._draw_neighbors(cur, sk, 1)
            prev, cur = cur, self._dp(nxt)
            walk.append(cur)
        walks = jnp.stack(walk, axis=1)  # [B, L+1] rows (0 = dead)
        src = walks[:, self._src_cols] * self._col_valid  # [B, M]
        ctx = walks[:, self._ctx_cols] * self._col_valid
        mask = (src > 0) & (ctx > 0)
        negs = self._draw_roots(
            kneg, self.batch_size * self.pairs_per_walk * self.num_negs
        )
        to_id = lambda r: self.node_id[r]  # noqa: E731  (-1 on padding)
        return {
            "src": self._dp(to_id(src.reshape(-1))),
            "pos": self._dp(to_id(ctx.reshape(-1))),
            "negs": self._dp(
                to_id(negs).reshape(-1, self.num_negs)
            ),
            "mask": self._dp(mask.reshape(-1)),
        }



class _FlatEdgeFlow(DeviceGraphTables):
    """Shared staging for flows that draw whole edges from the flat list
    (LINE, KG): edge columns + weight CDF + node tables for negatives."""

    def __init__(self, graph, batch_size: int, num_negs: int,
                 edge_type: int = -1, mesh=None, stage_er: bool = False):
        self.mesh = mesh
        self.batch_size = int(batch_size)
        self.num_negs = int(num_negs)
        self._stage_flat_edges(graph, edge_type, stage_er=stage_er)
        ids, wn, nt = _node_table(graph)
        self._stage_nodes(graph, ids, wn, nt, None, -1)


class DeviceEdgeFlow(_FlatEdgeFlow):
    """On-device weighted edge sampling for LINE (examples/line parity).

    Replaces the host `line_batches` source (graph.sample_edge +
    sample_node negatives, models/embedding_models.py). Stages the FLAT
    edge list — the right layout for whole-edge draws on any degree
    distribution (no max_degree guard; power-law graphs welcome) — and
    draws each edge with one searchsorted over the weight CDF, the same
    distribution the host alias tables sample. `sample(key)` returns the
    SkipGramModel dict batch.
    """

    def __init__(self, graph, batch_size: int, num_negs: int = 5,
                 edge_type: int = -1, mesh=None):
        super().__init__(graph, batch_size, num_negs, edge_type, mesh)

    def sample(self, key) -> dict:
        """key → SkipGramModel batch dict, jit-traceable."""
        kedge, kneg = jax.random.split(key)
        pick = self._draw_edges(kedge, self.batch_size)
        negs = self._draw_global_nodes(kneg, self.batch_size * self.num_negs)
        return {
            "src": self._dp(self.eh[pick]),
            "pos": self._dp(self.et[pick]),
            "negs": self._dp(
                self.node_id[negs].reshape(-1, self.num_negs)
            ),
            "mask": self._dp(jnp.ones(self.batch_size, bool)),
        }


class DeviceKGFlow(_FlatEdgeFlow):
    """On-device (h, r, t) triple sampling + corrupted negatives for the
    TransX family (models/kg.py `kg_batches` parity).

    KG graphs are exactly the power-law case where a padded [N, Dmax]
    adjacency is the wrong layout (FB15k hub entities have thousands of
    out-edges), so this flow stages the FLAT edge list (shared
    `_stage_flat_edges`: int32 (h, r, t) columns, 12 bytes/edge — 6 MB
    for FB15k's 483k triples — one searchsorted per draw, exact, any
    degree distribution). Corrupted heads/tails draw from the global
    node CDF (host sample_node(-1) parity). `sample(key)` returns the
    exact dict batch `TransX.__call__` consumes.
    """

    def __init__(self, graph, batch_size: int, num_negs: int = 8,
                 edge_type: int = -1, mesh=None):
        super().__init__(
            graph, batch_size, num_negs, edge_type, mesh, stage_er=True
        )

    def sample(self, key) -> dict:
        """key → TransX batch dict, jit-traceable."""
        kedge, kneg = jax.random.split(key)
        pick = self._draw_edges(kedge, self.batch_size)
        negs = self.node_id[
            self._draw_global_nodes(
                kneg, self.batch_size * self.num_negs * 2
            )
        ].reshape(2, self.batch_size, self.num_negs)
        return {
            "h": self._dp(self.eh[pick]),
            "r": self._dp(self.er[pick]),
            "t": self._dp(self.et[pick]),
            "neg_h": self._dp(negs[0]),
            "neg_t": self._dp(negs[1]),
        }


class DeviceRelationFlow(DeviceGraphTables):
    """On-device per-relation fanouts for RGCN (relation.py parity).

    One staged table set (adjacency + weight + type planes) serves every
    relation: each hop's per-relation draw masks the type plane before
    the CDF inversion (`_draw_neighbors_typed`), exactly the host
    sample_neighbor(cur, [r], k) distribution, without R per-relation
    adjacency copies. sample(key) returns the RelMiniBatch the RGCN
    model consumes, with dense features gathered in-flow from an HBM
    feature table (RelMiniBatch has no rows-mode hydration path).
    """

    _PAGED_OK = False  # typed draws mask the dense type plane

    def __init__(
        self,
        graph,
        feature_names,
        num_relations: int,
        batch_size: int,
        fanout: int = 5,
        num_hops: int = 2,
        label_feature: str | None = None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
    ):
        super().__init__(
            graph, None, max_degree, roots_pool, root_node_type, mesh,
            stage_types=True,
        )
        from euler_tpu.estimator.feature_cache import DeviceFeatureCache

        self.num_relations = int(num_relations)
        self.batch_size = int(batch_size)
        self.fanout = int(fanout)
        self.num_hops = int(num_hops)
        self.feat_table = DeviceFeatureCache(graph, list(feature_names)).table
        self.label_table = (
            DeviceFeatureCache(graph, [label_feature]).table
            if label_feature is not None
            else None
        )

    def sample(self, key) -> "RelMiniBatch":
        from euler_tpu.dataflow.relation import RelMiniBatch

        k, nr = self.fanout, self.num_relations
        keys = jax.random.split(key, 1 + self.num_hops * nr)
        cur = self._dp(self._draw_roots(keys[0], self.batch_size))
        hop_rows = [cur]
        hop_masks = [cur > 0]
        rel_blocks = []
        ki = 1
        for _ in range(self.num_hops):
            n = cur.shape[0]
            nxt = []
            blocks = []
            for r in range(nr):
                nbr, ew, valid = self._draw_neighbors_typed(
                    cur, keys[ki], k, r
                )
                ki += 1
                nxt.append(nbr.reshape(n, k))
                # src slots for relation r sit at rows [i*nr*k + r*k + j]
                src = (
                    np.arange(n)[:, None] * nr * k
                    + r * k
                    + np.arange(k)[None, :]
                ).reshape(-1)
                blocks.append(
                    Block(
                        edge_src=jnp.asarray(src, jnp.int32),
                        edge_dst=jnp.repeat(
                            jnp.arange(n, dtype=jnp.int32), k
                        ),
                        edge_w=self._dp(ew.astype(jnp.float32)),
                        mask=self._dp(valid),
                        n_src=n * nr * k,
                        n_dst=n,
                    )
                )
            rel_blocks.append(tuple(blocks))
            # next hop interleaves relations: [n, nr, k] flattened, same
            # slot layout the edge_src indices above address
            cur = self._dp(
                jnp.stack(nxt, axis=1).reshape(-1)
            )
            hop_rows.append(cur)
            hop_masks.append(cur > 0)
        feats = tuple(self._dp(self.feat_table[rw]) for rw in hop_rows)
        labels = (
            self._dp(self.label_table[hop_rows[0]])
            if self.label_table is not None
            else None
        )
        return RelMiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            rel_blocks=tuple(rel_blocks),
            root_idx=self._dp(self.node_id[hop_rows[0]]),
            labels=labels,
            hop_ids=tuple(
                self._dp(self.node_id[rw]) for rw in hop_rows
            ),
        )



class DeviceLayerwiseFlow(DeviceGraphTables):
    """On-device LADIES layer sampling (layerwise.py parity).

    Each layer draw IS the exact host algorithm as XLA ops: candidate
    incident weights scatter-add into an [N+1] vector, Gumbel top-k picks
    `count` layer nodes without replacement (log w + Gumbel noise — the
    store's layerwise_from_full recipe), and the dense batch→layer
    adjacency is a [W, D, count] membership einsum, row-normalized. When
    the whole frontier fits in `count` the layer is exact, like the host.
    sample(key) returns the LayerwiseBatch `LayerwiseGCN` consumes (dense
    in-flow-gathered features).
    """

    _PAGED_OK = False  # the layer scatter reads the dense adj/w planes

    def __init__(
        self,
        graph,
        feature_names,
        batch_size: int,
        layer_sizes=(128, 128),
        label_feature: str | None = None,
        normalize: bool = True,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        root_node_type: int = -1,
        mesh=None,
    ):
        super().__init__(
            graph, edge_types, max_degree, roots_pool, root_node_type, mesh
        )
        from euler_tpu.estimator.feature_cache import DeviceFeatureCache

        self.batch_size = int(batch_size)
        self.layer_sizes = [int(c) for c in layer_sizes]
        self.normalize = bool(normalize)
        self.feat_table = DeviceFeatureCache(graph, list(feature_names)).table
        self.label_table = (
            DeviceFeatureCache(graph, [label_feature]).table
            if label_feature is not None
            else None
        )

    def _sample_layer(self, cur, key, count: int):
        """[W] rows → ([count] layer rows, f32[W, count] adjacency,
        bool[count] layer mask)."""
        nbr = self.adj[cur]  # [W, D]
        w = (
            self.wtab[cur]
            if self.wtab is not None
            else (nbr > 0).astype(jnp.float32)
        )
        wsum = (
            jnp.zeros(self.num_nodes + 1)
            .at[nbr.reshape(-1)]
            .add(w.reshape(-1))
            .at[0]
            .set(0.0)
        )
        g = jax.random.gumbel(key, (self.num_nodes + 1,))
        score = jnp.where(wsum > 0, jnp.log(wsum) + g, -jnp.inf)
        top, layer = jax.lax.top_k(score, count)
        lmask = top > -jnp.inf
        layer = jnp.where(lmask, layer, 0).astype(jnp.int32)
        hit = (nbr[:, :, None] == layer[None, None, :]) & (
            layer[None, None, :] > 0
        )
        adj = jnp.einsum("wd,wdc->wc", w, hit.astype(w.dtype))
        if self.normalize:
            adj = adj / jnp.maximum(adj.sum(axis=1, keepdims=True), 1e-9)
        return layer, adj, lmask

    def sample(self, key) -> "LayerwiseBatch":
        from euler_tpu.dataflow.layerwise import LayerwiseBatch

        keys = jax.random.split(key, 1 + len(self.layer_sizes))
        cur = self._dp(self._draw_roots(keys[0], self.batch_size))
        layer_rows = [cur]
        layer_masks = [cur > 0]
        adjs = []
        for count, lk in zip(self.layer_sizes, keys[1:]):
            layer, adj, lmask = self._sample_layer(cur, lk, count)
            adjs.append(self._dp(adj))
            cur = self._dp(layer)
            layer_rows.append(cur)
            layer_masks.append(lmask)
        feats = tuple(self._dp(self.feat_table[rw]) for rw in layer_rows)
        labels = (
            self._dp(self.label_table[layer_rows[0]])
            if self.label_table is not None
            else None
        )
        return LayerwiseBatch(
            feats=feats,
            masks=tuple(layer_masks),
            adjs=tuple(adjs),
            root_idx=self._dp(self.node_id[layer_rows[0]]),
            labels=labels,
            hop_ids=tuple(self._dp(self.node_id[rw]) for rw in layer_rows),
        )



class DeviceGaeFlow(DeviceSageFlow):
    """On-device (src, dst, neg) fanout triples for GAE/VGAE
    (models/autoencoders.py `gae_batches` parity): src draws ∝ edge
    weight through the shared edge-source CDF, dst is the drawn edge's
    endpoint, neg is a global node draw; each gets its own fanout batch.
    """

    def __init__(self, graph, fanouts, batch_size, edge_types=None,
                 max_degree: int = 512, mesh=None, layout: str = "auto",
                 page_size: int = 16):
        super().__init__(
            graph, fanouts, batch_size, None, edge_types, max_degree,
            mesh=mesh, layout=layout, page_size=page_size,
        )
        self._stage_edge_src_cdf()

    def sample(self, key) -> tuple:
        ksrc, kdst, kneg, k1, k2, k3 = jax.random.split(key, 6)
        src = self._draw_edge_sources(ksrc, self.batch_size)
        dst, _, _ = self._draw_neighbors(src, kdst, 1)
        neg = self._draw_global_nodes(kneg, self.batch_size)
        return (
            self._fanout_batch(src, k1),
            self._fanout_batch(dst, k2),
            self._fanout_batch(neg, k3),
        )


class DeviceDgiFlow(DeviceSageFlow):
    """On-device (real, corrupted) batches for DGI (`dgi_batches`
    parity): corruption permutes the feature rows across the batch —
    with rows-mode feats a row permutation IS the standard DGI feature
    shuffle (hydration gathers the permuted rows into permuted dense
    features)."""

    def sample(self, key) -> tuple:
        kmb, kperm = jax.random.split(key)
        mb = super().sample(kmb)
        # one permutation per hop, shared by the feature rows and (when
        # with_hop_ids is on) the id plane: ids, features, and the masks
        # hydration derives from the rows must move together, or pad
        # slots in the un-permuted plane land under valid-mask positions
        perms = tuple(
            jax.random.permutation(pk, f.shape[0])
            for pk, f in zip(
                jax.random.split(kperm, len(mb.feats)), mb.feats
            )
        )
        perm_feats = tuple(f[p] for f, p in zip(mb.feats, perms))
        perm_ids = (
            tuple(h[p] for h, p in zip(mb.hop_ids, perms))
            if mb.hop_ids is not None
            else None
        )
        return (mb, mb.replace(feats=perm_feats, hop_ids=perm_ids))


class DeviceWholeGraphFlow(DeviceGraphTables):
    """Dataset-on-device whole-graph batches for graph classification
    (whole.py `WholeGraphDataFlow` + `graph_label_batches` parity).

    Graph-classification datasets are small (every labeled graph padded
    to max_nodes × max_degree), so the entire padded dataset stages into
    HBM once — per-graph feature/mask/edge/label tensors stacked along a
    leading graph axis — and a training batch is a uniform label draw
    (host sample_graph_label parity) plus gathers, with edge indices
    offset into the batch's flattened node table. Staging reuses the
    host flow's padding/slot logic by querying it one label at a time.
    """

    def __init__(
        self,
        graph,
        feature_names,
        batch_size: int,
        max_nodes: int = 32,
        max_degree: int = 8,
        edge_types=None,
        mesh=None,
        host_flow=None,
    ):
        """host_flow: an already-built WholeGraphDataFlow to stage from
        (its max_nodes/max_degree then govern the padding — callers that
        also evaluate through the host flow pass it to keep one source
        of truth); built internally otherwise."""
        from euler_tpu.dataflow.whole import WholeGraphDataFlow

        self.mesh = mesh
        self.batch_size = int(batch_size)
        host = host_flow or WholeGraphDataFlow(
            graph, feature_names, max_nodes=max_nodes,
            max_degree=max_degree, edge_types=edge_types,
        )
        if host.num_labels == 0:
            raise ValueError("graph has no graph labels to sample")
        self.num_classes = host.num_classes
        ng, nmax = host.num_labels, host.max_nodes
        # ONE batched host query stages every labeled graph; per-graph
        # tensors are reshaped slices (the host's i*nmax edge offsets are
        # subtracted here and re-added per batch slot in sample())
        all_b = host.query(np.arange(ng))
        put = jax.device_put
        self.gfeats = put(np.asarray(all_b.feats).reshape(ng, nmax, -1))
        self.gmask = put(np.asarray(all_b.node_mask).reshape(ng, nmax))
        self.grid = int(all_b.block.grid)
        e = nmax * self.grid
        local = np.arange(ng, dtype=np.int32)[:, None] * nmax
        emask = np.asarray(all_b.block.mask).reshape(ng, e)
        # masked padding edges carry global slot 0 in the host layout;
        # localize them to 0 (not -i*nmax) so the batch offset re-added in
        # sample() can never go negative
        self.gesrc = put(np.where(
            emask, np.asarray(all_b.block.edge_src).reshape(ng, e) - local, 0
        ).astype(np.int32))
        # dst is the aggregation center — structurally valid for masked
        # edges too, so plain localization stays in [0, nmax)
        self.gedst = put(
            (np.asarray(all_b.block.edge_dst).reshape(ng, e) - local).astype(
                np.int32
            )
        )
        self.gew = put(np.asarray(all_b.block.edge_w).reshape(ng, e))
        self.gemask = put(emask)
        self.glabels = put(np.asarray(all_b.labels))
        self.ghop = put(np.asarray(all_b.hop_ids).reshape(ng, nmax))
        self.nmax = nmax
        self.num_graphs = ng

    def sample(self, key) -> "GraphBatch":
        from euler_tpu.dataflow.whole import GraphBatch

        b, nmax = self.batch_size, self.nmax
        pick = jax.random.randint(key, (b,), 0, self.num_graphs)
        off_n = (jnp.arange(b, dtype=jnp.int32) * nmax)[:, None]
        block = Block(
            edge_src=self._dp((self.gesrc[pick] + off_n).reshape(-1)),
            edge_dst=self._dp((self.gedst[pick] + off_n).reshape(-1)),
            edge_w=self._dp(self.gew[pick].reshape(-1)),
            mask=self._dp(self.gemask[pick].reshape(-1)),
            n_src=b * nmax,
            n_dst=b * nmax,
            grid=self.grid,
        )
        return GraphBatch(
            feats=self._dp(self.gfeats[pick].reshape(b * nmax, -1)),
            node_mask=self._dp(self.gmask[pick].reshape(-1)),
            block=block,
            graph_ids=self._dp(
                jnp.repeat(jnp.arange(b, dtype=jnp.int32), nmax)
            ),
            labels=self._dp(self.glabels[pick]),
            hop_ids=self._dp(self.ghop[pick].reshape(-1)),
            n_graphs=b,
        )
