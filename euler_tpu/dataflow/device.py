"""Fully on-device GraphSAGE batch sampling.

The host flows (sage.py) sample subgraphs on the CPU and ship int32
feature rows over PCIe/network every step — the lean wire minimizes the
bytes, but a tunneled or remote device still pays per-dispatch transfer
for ~10^5 rows/step. This module removes the wire entirely: the padded
adjacency lives in HBM next to the feature cache, and every step of the
scanned train loop *traces* root sampling + multi-hop fanout as XLA ops.
Per-step host→device traffic is zero; the only inputs are PRNG keys.

This is the TPU-first answer to the reference's sample_fanout kernel
(euler/core/kernels/sample_fanout_op.cc and the TF custom op in
tf_euler/python/euler_ops/neighbor_ops.py): instead of a host-side C++
sampler feeding the accelerator, the sampler IS accelerator code — a
[N+1, D] int32 gather plus vectorized uniform draws, fused by XLA into
the same program as the model. Weighted graphs are first-class: edge
draws invert a per-row cumulative-weight CDF with a [W, k, D] compare-
reduce (pure VPU work; D is the guarded max degree), and weighted root
draws binary-search a uint32-quantized node-weight CDF — the same
weighted-with-replacement distribution the host samplers and the C++
engine's alias tables draw from (graph_engine.cc `AliasTable`). Batches
from a weighted graph carry bf16 edge weights, matching the host
weighted-lean wire (sage.py `_lean_w`) leaf-for-leaf.

Memory: the padded adjacency costs (N+1)·Dmax·4 bytes of HBM (row+1
encoding, 0 = padding). For bounded-degree graphs this is small (200k
nodes × deg 15 ≈ 12 MB); power-law graphs with hub nodes blow the table
up — `max_degree` (default 512) is a GUARD that fails construction
loudly in that case (truncating would bias sampling), and such graphs
keep the host flows.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import Block, MiniBatch

_STAGE_CHUNK = 16384


class DeviceSageFlow:
    """HBM-resident adjacency + traced fanout sampling → lean MiniBatch.

    Pass an instance as an Estimator's `batch_fn`: the Estimator detects
    `is_device_flow` and generates batches inside the jitted train step
    from per-step PRNG keys (estimator.py `_train_step_scan`). The batch
    pytree is identical to what a lean host `SageDataFlow` ships after
    device_put, so models, hydration, and the feature cache are shared.
    """

    is_device_flow = True

    def __init__(
        self,
        graph,
        fanouts,
        batch_size: int,
        label_feature: str | None = None,
        edge_types=None,
        max_degree: int = 512,
        roots_pool: np.ndarray | None = None,
        mesh=None,
    ):
        """roots_pool: optional node ids to sample roots from (e.g. a
        train split); default is every node. Root draws are proportional
        to node weights either way (uniform when weights are constant —
        host sample_node parity). max_degree is a guard on the
        staged adjacency width ((N+1)·Dmax·4 bytes of HBM): construction
        raises when the graph's true max degree exceeds it — truncation
        would bias sampling, so it is never done silently. The default
        (512) makes a hub-heavy power-law graph fail loudly instead of
        allocating an N×hub_degree table; raise it explicitly after
        checking the memory math.

        mesh: a jax.sharding.Mesh for data-parallel training — sampled
        batch leaves are sharding-constrained along the mesh's data axis
        (each device materializes only its own batch slice; the staged
        tables replicate), so one traced sample() drives every device.
        Values are identical to the unsharded program for the same key.
        """
        self.fanouts = [int(k) for k in fanouts]
        self.batch_size = int(batch_size)
        self.mesh = mesh
        if not all(
            hasattr(s, "node_ids") and hasattr(s, "node_weights")
            for s in graph.shards
        ):
            raise ValueError(
                "DeviceSageFlow stages the full adjacency host-side and "
                "needs local shards (remote graphs keep the host flows)"
            )
        ids = np.concatenate([np.asarray(s.node_ids) for s in graph.shards])
        n = len(ids)
        dmax = int(graph.max_degree(ids, edge_types))
        if dmax > max_degree:
            raise ValueError(
                f"graph max degree {dmax} exceeds max_degree={max_degree}; "
                "the staged adjacency would cost (N+1)*"
                f"{dmax}*4 bytes — raise the cap explicitly or use the "
                "host SageDataFlow"
            )
        adj = np.zeros((n + 1, dmax), dtype=np.int32)
        deg = np.zeros(n + 1, dtype=np.int32)
        wtab = np.zeros((n + 1, dmax), dtype=np.float32)
        unit_w = True
        for lo in range(0, n, _STAGE_CHUNK):
            sub = ids[lo : lo + _STAGE_CHUNK]
            nbr, w, _, mask, _ = graph.get_full_neighbor(
                sub, edge_types, max_degree=dmax
            )
            unit_w = unit_w and bool(np.all(w[mask] == 1.0))
            rows = graph.lookup_rows(nbr.ravel()).reshape(nbr.shape)
            # row+1 encoding, 0 = padding (matches DeviceFeatureCache's
            # zero row); masked or unknown neighbors collapse to padding
            block = np.where(mask & (rows >= 0), rows + 1, 0).astype(np.int32)
            # compact valid entries to the front so idx < deg hits them
            order = np.argsort(block == 0, axis=1, kind="stable")
            sl = slice(1 + lo, 1 + lo + len(sub))
            adj[sl, : block.shape[1]] = np.take_along_axis(block, order, axis=1)
            wtab[sl, : block.shape[1]] = np.take_along_axis(
                np.where(block > 0, w, 0.0).astype(np.float32), order, axis=1
            )
            deg[sl] = (block > 0).sum(axis=1)
        # a positive-degree row whose weights are all zero is unsampleable
        # (host _WeightedSampler semantics: zero total → padding)
        deg[wtab.sum(axis=1) <= 0.0] = 0
        self.adj = jax.device_put(adj)
        self.deg = jax.device_put(deg)
        self.unit_w = unit_w
        # inverse-CDF table: idx = #{t : cum[t] <= u·total} is a
        # [width, k, D] compare-reduce on device (D ≤ max_degree); the
        # raw weights are recovered as adjacent cum differences, so only
        # the cumulative table is staged
        self.cumw = None if unit_w else jax.device_put(np.cumsum(wtab, axis=1))
        # weight-proportional root draws (host sample_node parity): a
        # uint32-quantized CDF, binary-searched on device — over all nodes,
        # or over roots_pool's members when a pool restricts the draw.
        # Integer quantization keeps adjacent cum values exact where f32
        # cumsum over >1e6 nodes would swallow small weights.
        wn = np.concatenate(
            [np.asarray(s.node_weights, dtype=np.float64) for s in graph.shards]
        )
        pool_rows = None
        if roots_pool is not None:
            pool_rows = graph.lookup_rows(
                np.asarray(roots_pool, dtype=np.uint64)
            )
            if np.any(pool_rows < 0):
                raise ValueError("roots_pool contains unknown node ids")
            wn = wn[pool_rows]
        self.node_cdf = None
        if wn.size and not np.all(wn == wn[0]):
            cum = np.cumsum(wn)
            if cum[-1] <= 0:
                raise ValueError("root node weights sum to zero")
            self.node_cdf = jax.device_put(
                np.floor(cum / cum[-1] * np.float64(2**32 - 1)).astype(
                    np.uint32
                )
            )
        # int32 view of the u64 id space for root_idx (same truncation the
        # host flows apply); index 0 (padding) maps to -1
        node_id = np.full(n + 1, -1, dtype=np.int32)
        node_id[1:] = ids.astype(np.int64).astype(np.int32)
        self.node_id = jax.device_put(node_id)
        self.roots = (
            jax.device_put(pool_rows.astype(np.int32) + 1)
            if pool_rows is not None
            else None
        )
        self.num_nodes = n
        if label_feature is not None:
            from euler_tpu.estimator.feature_cache import DeviceFeatureCache

            self.label_table = DeviceFeatureCache(graph, [label_feature]).table
        else:
            self.label_table = None

    def _dp(self, x):
        """Constrain a batch-leading array to the mesh's data axis (same
        divisibility rule as parallel.shard_batch); no-op without a mesh."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        from euler_tpu.parallel import DATA_AXIS

        nd = self.mesh.shape[DATA_AXIS]
        spec = P(DATA_AXIS) if x.ndim >= 1 and x.shape[0] % nd == 0 else P()
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec)
        )

    def sample(self, key) -> MiniBatch:
        """key → lean MiniBatch, jit-traceable (call inside the train step)."""
        keys = jax.random.split(key, 1 + len(self.fanouts))
        if self.node_cdf is not None:
            # weight-proportional draw over the pool (or all nodes)
            r = jax.random.bits(keys[0], (self.batch_size,), dtype=jnp.uint32)
            pick = jnp.searchsorted(self.node_cdf, r, side="right")
            pick = jnp.minimum(pick, len(self.node_cdf) - 1).astype(jnp.int32)
            cur = self.roots[pick] if self.roots is not None else pick + 1
        elif self.roots is not None:
            pick = jax.random.randint(
                keys[0], (self.batch_size,), 0, len(self.roots)
            )
            cur = self.roots[pick]
        else:
            cur = jax.random.randint(
                keys[0], (self.batch_size,), 1, self.num_nodes + 1
            )
        cur = self._dp(cur)
        feats = [cur]
        blocks = []
        width = self.batch_size
        for k, hk in zip(self.fanouts, keys[1:]):
            deg = self.deg[cur]  # [width]
            u = jax.random.uniform(hk, (width, k))
            if self.unit_w:
                idx = (u * deg[:, None]).astype(jnp.int32)
                ew = None
            else:
                cw = self.cumw[cur]  # [width, D]
                scaled = u * cw[:, -1][:, None]
                idx = (cw[:, None, :] <= scaled[:, :, None]).sum(axis=-1)
            idx = jnp.minimum(idx, jnp.maximum(deg[:, None] - 1, 0))
            nbr = jnp.where(
                deg[:, None] > 0, self.adj[cur[:, None], idx], 0
            ).reshape(-1)
            nbr = self._dp(nbr)
            if not self.unit_w:
                # weighted-lean wire parity: bf16 weights ride the batch.
                # w[idx] = cum[idx] - cum[idx-1]; zero on padded slots
                # (their cum rows are all zero)
                hi = jnp.take_along_axis(cw, idx, axis=1)
                lo = jnp.where(
                    idx > 0,
                    jnp.take_along_axis(cw, jnp.maximum(idx - 1, 0), axis=1),
                    0.0,
                )
                ew = self._dp((hi - lo).reshape(-1).astype(jnp.bfloat16))
            blocks.append(
                Block(
                    edge_src=None, edge_dst=None, edge_w=ew, mask=None,
                    n_src=width * k, n_dst=width, grid=k,
                )
            )
            feats.append(nbr)
            cur = nbr
            width *= k
        labels = (
            self.label_table[feats[0]] if self.label_table is not None else None
        )
        if labels is not None:
            labels = self._dp(labels)
        return MiniBatch(
            feats=tuple(feats),
            masks=None,
            blocks=tuple(blocks),
            root_idx=self._dp(self.node_id[feats[0]]),
            labels=labels,
            hop_ids=None,
        )

    def __call__(self):
        raise TypeError(
            "DeviceSageFlow is not a host batch_fn; pass it to an Estimator "
            "(detected via is_device_flow) or call .sample(key) inside jit"
        )
