"""Whole-graph batches for graph classification.

The reference path (SURVEY.md §3.6): `sample_graph_label` →
`get_graph_by_label` → WholeDataFlow + GraphGNNNet with graph pooling
(tf_euler/python/dataflow/whole_dataflow.py, mp_utils/base_graph.py:24-47).
The TPU shape discipline: G graphs per batch, each padded to `max_nodes`
slots and `max_nodes * max_degree` edge slots, with segment ids for
graph-level pooling.
"""

from __future__ import annotations

import flax.struct
import jax
import numpy as np

from euler_tpu.dataflow.base import Block, DataFlow
from euler_tpu.graph.store import DEFAULT_ID

Array = jax.Array


@flax.struct.dataclass
class GraphBatch:
    """G whole graphs flattened into one padded node/edge table."""

    feats: Array  # f32[G*Nmax, F]
    node_mask: Array  # bool[G*Nmax]
    block: Block  # intra-batch edges (src/dst index the node table)
    graph_ids: Array  # int32[G*Nmax] segment id per node slot
    labels: Array  # f32[G, L] (one-hot / multi-hot)
    hop_ids: Array | None = None  # int32[G*Nmax]
    n_graphs: int = flax.struct.field(pytree_node=False, default=0)


class WholeGraphDataFlow(DataFlow):
    """Builds GraphBatch for a list of graph labels."""

    def __init__(
        self,
        graph,
        feature_names,
        max_nodes: int = 32,
        max_degree: int = 8,
        edge_types=None,
        label_to_onehot: bool = True,
        rng=None,
    ):
        super().__init__(graph, feature_names, rng=rng)
        self.max_nodes = max_nodes
        self.max_degree = max_degree
        self.edge_types = edge_types
        self.num_labels = len(graph.meta.graph_labels)
        self.label_to_onehot = label_to_onehot
        # Class extraction (base_graph.py:33 parity — the reference feeds
        # per-graph CLASS labels to the loss, not graph identity): label
        # strings ending in "_c<k>" (the converter's graph-label format,
        # e.g. "g17_c1") classify into k; when every label carries one,
        # batches are one-hot over the distinct classes. Otherwise each
        # label is its own class (identity), the legacy behavior.
        import re

        parsed = [
            re.search(r"_c(-?\d+)$", s) for s in graph.meta.graph_labels
        ]
        uniq = (
            sorted({int(m.group(1)) for m in parsed})
            if self.num_labels and all(parsed)
            else []
        )
        if len(uniq) >= 2:  # a single parsed class would silently
            # broadcast (g, 1) labels against multi-class logits —
            # degenerate label sets keep the identity mapping instead
            self.label_class = np.asarray(
                [uniq.index(int(m.group(1))) for m in parsed], np.int64
            )
            self.num_classes = len(uniq)
        else:
            self.label_class = np.arange(max(self.num_labels, 1))
            self.num_classes = max(self.num_labels, 1)

    def query(self, label_ids: np.ndarray) -> GraphBatch:
        label_ids = np.asarray(label_ids, dtype=np.int64)
        g = len(label_ids)
        nmax = self.max_nodes
        node_tab = np.full((g, nmax), DEFAULT_ID, dtype=np.uint64)
        groups = self.graph.get_graph_by_label(label_ids)
        for i, nodes in enumerate(groups):
            nodes = nodes[:nmax]
            node_tab[i, : len(nodes)] = nodes
        flat = node_tab.reshape(-1)
        node_mask = flat != DEFAULT_ID

        # intra-graph edges: neighbors restricted to this graph's node set
        nbr, w, _, mask, _ = self.graph.get_full_neighbor(
            flat, self.edge_types, max_degree=self.max_degree
        )
        d = nbr.shape[1]
        # map neighbor ids → slot in this graph's row of the node table
        gi = np.repeat(np.arange(g), nmax)  # graph of each src slot
        slot = np.full((g * nmax, d), -1, dtype=np.int64)
        for i in range(g):
            row_nodes = node_tab[i]
            sel = slice(i * nmax, (i + 1) * nmax)
            pos = np.searchsorted(row_nodes[: len(groups[i][:nmax])], nbr[sel])
            pos = np.clip(pos, 0, nmax - 1)
            hit = mask[sel] & (node_tab[i][pos] == nbr[sel])
            slot[sel] = np.where(hit, pos + i * nmax, -1)
        # aggregation at each center node: dst = the node whose neighbors we
        # fetched, src = the neighbor's slot in the same node table
        center = np.repeat(np.arange(g * nmax, dtype=np.int32), d)
        nbr_slot = slot.reshape(-1)
        edge_mask = nbr_slot >= 0
        nbr_slot = np.where(edge_mask, nbr_slot, 0).astype(np.int32)
        block = Block(
            edge_src=nbr_slot,
            edge_dst=center,
            edge_w=np.where(edge_mask, w.reshape(-1), 0.0).astype(np.float32),
            mask=edge_mask,
            n_src=g * nmax,
            n_dst=g * nmax,
            grid=d,
        )

        labels = np.zeros((g, self.num_classes), dtype=np.float32)
        if self.label_to_onehot:
            cls = self.label_class[
                np.clip(label_ids, 0, len(self.label_class) - 1)
            ]
            labels[np.arange(g), cls] = 1.0
        # node_feats_hops dedups the flattened table before the fetch —
        # padding slots (all DEFAULT_ID) and shared nodes cost one row
        (feats,) = self.node_feats_hops([flat])
        return GraphBatch(
            feats=feats,
            node_mask=node_mask,
            block=block,
            graph_ids=np.repeat(np.arange(g, dtype=np.int32), nmax),
            labels=labels,
            hop_ids=flat.astype(np.int64).astype(np.int32),
            n_graphs=g,
        )


class FullGraphFlow(DataFlow):
    """Full-batch node classification over the ENTIRE graph (transductive).

    The cora-class GCN recipe (examples/gcn: every node + every edge in one
    batch, loss on the train split only). One node table X[N, F] and one
    edge Block are built once and reused for all `num_hops` layers —
    `query(roots)` only swaps which rows carry loss (`target_idx`). With
    gcn_norm=True the block carries true degrees, so GCNConv runs the exact
    Â = D̂^-1/2 (A+I) D̂^-1/2 propagation of the GCN paper rather than the
    sampled-flow in-batch approximation (gcn_conv.py:32-54).
    """

    def __init__(
        self,
        graph,
        feature_names,
        label_feature: str,
        num_hops: int = 2,
        edge_types=None,
        gcn_norm: bool = True,
        add_self_loops: bool = False,
        rng=None,
    ):
        """add_self_loops appends one unit-weight (i, i) edge per node
        (UniqueDataFlow add_self_loops parity, neighbor_dataflow.py:27) —
        attention-style convs then let every node attend to itself without
        an architecture-side skip term. It also disables gcn_norm's degree
        attachment: GCNConv's Â = D̂^-1/2(A+I)D̂^-1/2 already contains the
        implicit self-loop, so feeding it explicit loops on top would
        double-count the self term — use one or the other."""
        if add_self_loops:
            gcn_norm = False
        super().__init__(graph, feature_names, label_feature, rng=rng)
        self.num_hops = num_hops
        if not all(hasattr(s, "node_ids") for s in graph.shards):
            raise ValueError(
                "FullGraphFlow needs local shards (it reads the whole node"
                " and edge tables at construction); for remote graphs use a"
                " sampled flow or load the data locally"
            )
        # global sorted node table: all shard ids, one row per node
        ids = np.sort(
            np.concatenate([np.asarray(s.node_ids) for s in graph.shards])
        ).astype(np.uint64)
        self.ids = ids
        self.X = self.node_feats(ids)
        self.Y = graph.get_dense_feature(ids, [label_feature])
        # full (directed) edge list mapped to table rows
        srcs, dsts, ws = [], [], []
        for s in graph.shards:
            keep = (
                np.isin(np.asarray(s.edge_types), list(edge_types))
                if edge_types is not None
                else slice(None)
            )
            srcs.append(np.asarray(s.edge_src)[keep])
            dsts.append(np.asarray(s.edge_dst)[keep])
            ws.append(np.asarray(s.edge_weights)[keep])
        n = len(ids)

        def rows_of(vals):  # id → table row, verified (dangling → -1)
            pos = np.clip(np.searchsorted(ids, vals), 0, n - 1)
            return np.where(ids[pos] == vals, pos, -1).astype(np.int32)

        src = rows_of(np.concatenate(srcs))
        dst = rows_of(np.concatenate(dsts))
        ok = (src >= 0) & (dst >= 0)  # drop edges with dangling endpoints
        src, dst = src[ok], dst[ok]
        w = np.concatenate(ws).astype(np.float32)[ok]
        if add_self_loops:
            loops = np.arange(n, dtype=np.int32)
            src = np.concatenate([src, loops])
            dst = np.concatenate([dst, loops])
            w = np.concatenate([w, np.ones(n, np.float32)])
        deg = np.asarray(
            graph.degree_sum(ids, edge_types), np.float32
        )
        self.block = Block(
            edge_src=src,
            edge_dst=dst,
            edge_w=w,
            mask=np.ones(len(src), dtype=bool),
            n_src=n,
            n_dst=n,
            src_deg=deg if gcn_norm else None,
            dst_deg=deg if gcn_norm else None,
        )
        self._ones = np.ones(n, dtype=bool)

    def query(self, roots: np.ndarray) -> "MiniBatch":
        from euler_tpu.dataflow.base import MiniBatch

        roots = np.asarray(roots, dtype=np.uint64)
        rows = np.clip(np.searchsorted(self.ids, roots), 0, len(self.ids) - 1)
        missing = self.ids[rows] != roots
        if missing.any():
            raise ValueError(
                f"{int(missing.sum())} root id(s) not in the graph "
                f"(e.g. {roots[missing][:3].tolist()})"
            )
        rows = rows.astype(np.int32)
        k = self.num_hops
        return MiniBatch(
            feats=(self.X,) * (k + 1),
            masks=(self._ones,) * (k + 1),
            blocks=(self.block,) * k,
            root_idx=rows,
            labels=self.Y[rows],
            target_idx=rows,
        )


def graph_label_batches(graph, flow: WholeGraphDataFlow, batch_size: int, rng=None):
    """Training source: sampled graph labels → whole-graph batches
    (graph_estimator parity)."""
    rng = rng if rng is not None else np.random.default_rng()

    def fn():
        labels = graph.sample_graph_label(batch_size, rng=rng)
        return (flow.query(labels),)

    return fn
