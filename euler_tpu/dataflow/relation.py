"""Per-relation dataflow for RGCN (RelationDataFlow parity,
tf_euler/python/dataflow/relation_dataflow.py): each hop carries one Block
per edge type so relation-specific transforms stay separable."""

from __future__ import annotations

import flax.struct
import jax
import numpy as np

from euler_tpu.dataflow.base import Block, DataFlow
from euler_tpu.graph.store import DEFAULT_ID

Array = jax.Array


@flax.struct.dataclass
class RelMiniBatch:
    feats: tuple  # f32[N_i, F] per hop
    masks: tuple  # bool[N_i]
    rel_blocks: tuple  # per hop: tuple of Blocks, one per relation
    root_idx: Array
    labels: Array | None = None
    hop_ids: tuple | None = None


class RelationDataFlow(DataFlow):
    """Fixed per-relation fanout at every hop."""

    def __init__(
        self,
        graph,
        feature_names,
        num_relations: int,
        fanout: int = 5,
        num_hops: int = 2,
        label_feature=None,
        label_dim=None,
        rng=None,
    ):
        super().__init__(graph, feature_names, label_feature, label_dim, rng)
        self.num_relations = num_relations
        self.fanout = fanout
        self.num_hops = num_hops

    def query(self, roots: np.ndarray) -> RelMiniBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        hop_ids = [roots]
        hop_masks = [roots != DEFAULT_ID]
        rel_blocks = []
        cur = roots
        k, nr = self.fanout, self.num_relations
        for _ in range(self.num_hops):
            n = len(cur)
            # next hop holds nr * k slots per node: [n, nr, k] flattened
            nxt = np.full((n, nr, k), DEFAULT_ID, dtype=np.uint64)
            blocks = []
            for r in range(nr):
                nbr, w, _, mask, _ = self.graph.sample_neighbor(
                    cur, [r], k, rng=self.rng
                )
                nxt[:, r, :] = nbr
                # src slots for relation r sit at rows [i*nr*k + r*k + j]
                src = (
                    np.arange(n)[:, None] * nr * k
                    + r * k
                    + np.arange(k)[None, :]
                ).reshape(-1)
                blocks.append(
                    Block(
                        edge_src=src.astype(np.int32),
                        edge_dst=np.repeat(np.arange(n, dtype=np.int32), k),
                        edge_w=w.reshape(-1).astype(np.float32),
                        mask=mask.reshape(-1),
                        n_src=n * nr * k,
                        n_dst=n,
                    )
                )
            rel_blocks.append(tuple(blocks))
            cur = nxt.reshape(-1)
            hop_ids.append(cur)
            hop_masks.append(cur != DEFAULT_ID)
        feats = tuple(self.node_feats(ids) for ids in hop_ids)
        return RelMiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            rel_blocks=tuple(rel_blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )
