"""Padded mini-batch subgraph containers + base dataflow.

The reference's `DataFlow`/`Block` abstraction (tf_euler/python/dataflow/
base_dataflow.py:23-52) builds *dynamic* subgraphs with `tf.unique`; XLA needs
static shapes, so the TPU design pads instead (SURVEY.md §7): hop i holds
exactly batch * prod(fanouts[:i]) node slots, invalid slots carry a mask, and
every downstream op is a fixed-shape gather/segment op — fusable by XLA and
trivially shardable along the batch axis of a device mesh.

A `Block` is the bipartite edge set between hop i+1 ("src", the sampled
neighbors) and hop i ("dst"); node tables are per-hop feature matrices.
"""

from __future__ import annotations

import flax.struct
import jax
import numpy as np

Array = jax.Array


@flax.struct.dataclass
class Block:
    """Edges from a src node table into a dst node table (one hop)."""

    edge_src: Array  # int32[E] rows into the src hop table
    edge_dst: Array  # int32[E] rows into the dst hop table
    edge_w: Array  # f32[E] edge weights (0 where masked)
    mask: Array  # bool[E] valid-edge mask
    n_src: int = flax.struct.field(pytree_node=False)
    n_dst: int = flax.struct.field(pytree_node=False)
    # >0 when edges are grid-structured (dst row i owns slots [i*g, (i+1)*g));
    # unlocks the fused Pallas gather+reduce path
    grid: int = flax.struct.field(pytree_node=False, default=0)
    # optional TRUE graph degrees (f32, self-loop not included): full-graph
    # degrees of the src/dst hop's nodes, for exact GCN symmetric
    # normalization in full-neighbor/whole-graph flows (the reference
    # computes in-batch degrees, gcn_conv.py:32-54, which only equal true
    # degrees when every incident edge is present in the block)
    src_deg: Array | None = None  # f32[n_src]
    dst_deg: Array | None = None  # f32[n_dst]


@flax.struct.dataclass
class MiniBatch:
    """One padded multi-hop subgraph batch, ready for device_put.

    feats[i]  — f32[N_i, F] node features of hop i (hop 0 = roots)
    masks[i]  — bool[N_i] node validity
    blocks[i] — edges hop i+1 → hop i  (len == num hops)
    root_idx  — int32[B] root node ids (for embedding lookups / neg sampling)
    labels    — optional f32[B, L] supervised targets
    """

    feats: tuple
    masks: tuple
    blocks: tuple
    root_idx: Array
    labels: Array | None = None
    hop_ids: tuple | None = None  # int32 per-hop node ids (for id embeddings)
    # whole-graph flows: rows of the hop-0 table whose outputs participate
    # in the loss/metric (labels then has one row per target); None means
    # every hop-0 row is a target (the sampled-flow contract)
    target_idx: Array | None = None


def gather_unique(ids_list, fetch):
    """Cross-hop unique-ID coalescing: ONE deduplicated fetch covers
    every hop, results scattered back by inverse index.

    A 2-hop SAGE batch re-cites the same hot node in (on power-law
    graphs) most of its slots, and cites hop-1 nodes again in hop 2 —
    fetching per hop ships every duplicate id AND its result row L×.
    `fetch(uniq)` sees each id once; because the fetched verbs are
    deterministic per id, `fetch(uniq)[inverse]` is bit-identical to
    fetching each hop directly.

    ids_list: 1-D id (or row) arrays. fetch(uniq) -> array whose leading
    dim is len(uniq). Returns one array per input list, same leading
    lengths, remaining dims from the fetch result.
    """
    arrs = [np.asarray(a).reshape(-1) for a in ids_list]
    flat = np.concatenate(arrs) if arrs else np.empty(0, np.uint64)
    uniq, inv = np.unique(flat, return_inverse=True)
    vals = np.asarray(fetch(uniq))
    ndup = int(flat.size - uniq.size)
    if ndup and len(uniq):
        from euler_tpu.distributed.cache import note_gather_dedup

        note_gather_dedup(ndup, vals.nbytes // len(uniq))
    out_flat = vals[inv]
    offs = np.cumsum([0] + [a.size for a in arrs])
    return [out_flat[offs[i] : offs[i + 1]] for i in range(len(arrs))]


class DataFlow:
    """Base: fetches features/labels; subclasses build the hop structure.

    query(roots) → MiniBatch of numpy arrays (host); training loops
    device_put them (or feed through an infeed pipeline).
    """

    def __init__(
        self,
        graph,
        feature_names: list[str],
        label_feature: str | None = None,
        label_dim: int | None = None,
        rng: np.random.Generator | None = None,
        feature_mode: str = "dense",
    ):
        self.graph = graph
        self.feature_names = list(feature_names)
        self.label_feature = label_feature
        self.label_dim = label_dim
        self.rng = rng if rng is not None else np.random.default_rng()
        if feature_mode not in ("dense", "rows"):
            raise ValueError(f"unknown feature_mode {feature_mode!r}")
        self.feature_mode = feature_mode

    # -- helpers ---------------------------------------------------------

    def node_feats(self, ids: np.ndarray) -> np.ndarray:
        if self.feature_mode == "rows":
            # ship int32 rows into a DeviceFeatureCache table instead of the
            # dense payload; row 0 is the cache's zero/padding row
            rows = self.graph.lookup_rows(ids)
            return np.where(rows >= 0, rows + 1, 0).astype(np.int32)
        if not self.feature_names:
            return np.zeros((len(ids), 0), dtype=np.float32)
        return self.graph.get_dense_feature(ids, self.feature_names)

    def node_feats_hops(self, ids_list) -> tuple:
        """Per-hop `node_feats`, with ids deduplicated ACROSS hops before
        the (possibly remote) fetch — one unique-id round instead of L+1
        rounds re-shipping every duplicate's feature row. Bit-identical
        to `tuple(self.node_feats(ids) for ids in ids_list)`."""
        if self.feature_mode == "rows":
            def fetch(u):
                rows = np.asarray(self.graph.lookup_rows(u))
                return np.where(rows >= 0, rows + 1, 0).astype(np.int32)
        elif not self.feature_names:
            return tuple(
                np.zeros((len(np.asarray(i)), 0), np.float32)
                for i in ids_list
            )
        else:
            def fetch(u):
                return self.graph.get_dense_feature(u, self.feature_names)
        return tuple(gather_unique(ids_list, fetch))

    def labels_of(self, ids: np.ndarray) -> np.ndarray | None:
        if self.label_feature is None:
            return None
        return self.graph.get_dense_feature(ids, [self.label_feature])

    def query(self, roots: np.ndarray) -> MiniBatch:
        raise NotImplementedError

    def query_padded(
        self, roots: np.ndarray, batch_size: int
    ) -> tuple[MiniBatch, int]:
        """query() at a FIXED root count: pads `roots` to `batch_size` by
        repeating the final id, so callers with variable request sizes
        (online serving buckets, tail inference chunks) always execute the
        one program compiled for that size. Returns (batch, n_valid) —
        rows [n_valid:] of the output are padding and must be sliced off."""
        roots = np.asarray(roots, dtype=np.uint64)
        n = len(roots)
        if n == 0 or n > batch_size:
            raise ValueError(
                f"need 1..{batch_size} roots for this bucket, got {n}"
            )
        if n < batch_size:
            roots = np.concatenate(
                [roots, np.repeat(roots[-1:], batch_size - n)]
            )
        return self.query(roots), n


def fanout_block(
    batch: int,
    fanout: int,
    w: np.ndarray,
    mask: np.ndarray,
    lazy: bool = False,
    ship_w: bool = True,
    ship_mask: bool = True,
    w_dtype=np.float32,
) -> Block:
    """Block for sampled fanout: src j feeds dst j // fanout.

    lazy=True skips materializing edge_src/edge_dst — they are a pure
    function of (batch, fanout), so shipping them to the device every step
    wastes host→device bandwidth; `hydrate_blocks` rebuilds them on device.
    ship_mask=False / ship_w=False likewise omit the edge mask / weights
    from the wire: hydrate_blocks rederives the mask from the rows-mode
    validity of the src hop and sets edge_w to exactly 1.0 where valid.
    Only valid for rows-mode batches whose consumer is weight-agnostic
    (mask-normalized mean/attention aggregators) or whose graph weights
    are all 1.0 — a uniform weight c != 1 would be rebuilt as 1.
    w_dtype picks the wire dtype for shipped weights; the weighted-lean
    path ships bfloat16 (half the bytes, graph weights need no more
    precision) and hydrate_blocks upcasts on device.
    """
    e = batch * fanout
    return Block(
        edge_src=None if lazy else np.arange(e, dtype=np.int32),
        edge_dst=None if lazy else np.repeat(
            np.arange(batch, dtype=np.int32), fanout
        ),
        edge_w=w.reshape(-1).astype(w_dtype) if ship_w else None,
        mask=mask.reshape(-1) if ship_mask else None,
        n_src=e,
        n_dst=batch,
        grid=fanout,
    )


def upgrade_lean_host(batch: MiniBatch) -> MiniBatch:
    """Host-side (numpy) rebuild of a LEAN batch's masks and edge weights,
    giving it the same pytree structure as a downgraded batch from the
    same lean flow. Exact for batches that satisfy the lean invariants
    (unit weights, no id aliasing, no dangling rows) — which is every
    batch a lean flow actually shipped lean. Lets steps_per_call windows
    that mix lean and downgraded batches stack instead of crashing."""
    if not isinstance(batch, MiniBatch) or batch.masks is not None:
        return batch
    masks = tuple(
        (np.asarray(f) > 0)
        if np.issubdtype(np.asarray(f).dtype, np.integer)
        else np.ones(np.asarray(f).shape[0], bool)
        for f in batch.feats
    )
    masks = (np.asarray(batch.root_idx) != -1,) + masks[1:]
    blocks = []
    for h, b in enumerate(batch.blocks):
        if b.mask is None:
            b = b.replace(mask=masks[h + 1].reshape(-1))
        if b.edge_w is None:
            b = b.replace(edge_w=np.asarray(b.mask, np.float32))
        elif np.asarray(b.edge_w).dtype != np.float32:
            b = b.replace(  # weighted-lean wire ships bf16
                edge_w=np.asarray(b.edge_w, np.float32)
            )
        blocks.append(b)
    return batch.replace(masks=masks, blocks=tuple(blocks))


def hydrate_blocks(batch: MiniBatch) -> MiniBatch:
    """Rebuild wire-omitted batch pieces on device (jit-safe).

    - lazy grid blocks' edge ids: on-device iota
    - batch.masks is None (lean wire): node validity = rows-mode feat > 0
    - block.mask is None: the src hop's node mask (grid layout aligns them)
    - block.edge_w is None: uniform weights (mask as f32)
    """
    import jax.numpy as jnp

    if not isinstance(batch, MiniBatch):
        return batch
    masks = batch.masks
    if masks is None:  # lean wire: validity rides the int32 rows (0 = pad)
        masks = tuple(
            (f > 0)
            if jnp.issubdtype(jnp.asarray(f).dtype, jnp.integer)
            else jnp.ones(f.shape[0], bool)
            for f in batch.feats
        )
        # hop 0 keeps the non-lean invariant: any non-DEFAULT_ID root is
        # valid even when absent from the feature store (its features are
        # the zero row). root_idx truncates DEFAULT_ID to int32 -1.
        masks = (batch.root_idx != -1,) + masks[1:]
    blocks = []
    for h, b in enumerate(batch.blocks):
        if b.mask is None:
            b = b.replace(mask=masks[h + 1].reshape(-1))
        if b.edge_w is None:
            b = b.replace(edge_w=b.mask.astype(jnp.float32))
        elif jnp.asarray(b.edge_w).dtype != jnp.float32:
            b = b.replace(  # weighted-lean wire ships bf16; upcast on device
                edge_w=jnp.asarray(b.edge_w).astype(jnp.float32)
            )
        if b.edge_src is None:
            b = b.replace(
                edge_src=jnp.arange(b.n_src, dtype=jnp.int32),
                edge_dst=jnp.repeat(
                    jnp.arange(b.n_dst, dtype=jnp.int32), b.grid
                ),
            )
        blocks.append(b)
    if masks is batch.masks and all(
        a is b for a, b in zip(blocks, batch.blocks)
    ):
        return batch
    return batch.replace(masks=masks, blocks=tuple(blocks))
