"""Sampled-fanout dataflow (GraphSAGE) — SageDataFlow parity
(tf_euler/python/dataflow/sage_dataflow.py:35-50) with padded static shapes.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.dataflow.base import DataFlow, MiniBatch, fanout_block
from euler_tpu.graph.store import DEFAULT_ID


class SageDataFlow(DataFlow):
    def __init__(
        self,
        graph,
        feature_names,
        edge_types=None,
        fanouts=(10, 10),
        label_feature=None,
        label_dim=None,
        rng=None,
        feature_mode="dense",
        lazy_blocks: bool = False,
    ):
        super().__init__(
            graph, feature_names, label_feature, label_dim, rng, feature_mode
        )
        self.edge_types = edge_types
        self.fanouts = list(fanouts)
        self.lazy_blocks = lazy_blocks

    @property
    def num_hops(self) -> int:
        return len(self.fanouts)

    def query(self, roots: np.ndarray) -> MiniBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        fused = getattr(self.graph, "fanout_with_rows", None)
        res = (
            fused(roots, self.edge_types, self.fanouts, rng=self.rng)
            if fused is not None
            else None
        )
        if res is not None:
            # fused path: one native-engine call yields every hop's ids,
            # weights, masks AND feature-cache rows
            hop_ids, hop_w, _, hop_masks, hop_rows = res
            # hop-0 validity matches the fallback path (any non-default id
            # counts, even if absent from the store — its features are zero)
            hop_masks = [roots != DEFAULT_ID] + list(hop_masks[1:])
            blocks = []
            width = len(roots)
            for k, w, mask in zip(self.fanouts, hop_w[1:], hop_masks[1:]):
                blocks.append(
                    fanout_block(width, k, w, mask, lazy=self.lazy_blocks)
                )
                width *= k
            if self.feature_mode == "rows":
                feats = tuple(
                    np.where(r >= 0, r + 1, 0).astype(np.int32)
                    for r in hop_rows
                )
            elif self.feature_names and hasattr(
                self.graph, "get_dense_by_rows"
            ):
                # reuse the rows the fanout already resolved — no second
                # per-id lookup pass (the facade splits global rows back to
                # their owner shards on partitioned graphs)
                try:
                    feats = tuple(
                        self.graph.get_dense_by_rows(r, self.feature_names)
                        for r in hop_rows
                    )
                except RuntimeError as e:
                    # capability gap only (older server / no row space):
                    # fall back to per-id fetch; real failures must surface
                    if "unknown op" in str(e) or "num_nodes" in str(e):
                        feats = tuple(
                            self.node_feats(ids) for ids in hop_ids
                        )
                    else:
                        raise
            else:
                feats = tuple(self.node_feats(ids) for ids in hop_ids)
        else:
            hop_ids = [roots]
            hop_masks = [roots != DEFAULT_ID]
            blocks = []
            cur = roots
            for k in self.fanouts:
                nbr, w, _, mask, _ = self.graph.sample_neighbor(
                    cur, self.edge_types, k, rng=self.rng
                )
                blocks.append(
                    fanout_block(len(cur), k, w, mask, lazy=self.lazy_blocks)
                )
                cur = nbr.reshape(-1)
                hop_ids.append(cur)
                hop_masks.append(mask.reshape(-1))
            # padded slots hold DEFAULT_ID → feature fetch returns zeros
            feats = tuple(self.node_feats(ids) for ids in hop_ids)
        return MiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )


class FullNeighborDataFlow(DataFlow):
    """Full-neighbor dataflow (GCNDataFlow parity) with a degree cap.

    Every hop expands each node to its full (capped) neighbor list; the cap
    keeps shapes static — the padded analog of gcn_dataflow.py.
    """

    def __init__(
        self,
        graph,
        feature_names,
        edge_types=None,
        num_hops=2,
        max_degree=32,
        label_feature=None,
        label_dim=None,
        rng=None,
        feature_mode="dense",
    ):
        super().__init__(
            graph, feature_names, label_feature, label_dim, rng, feature_mode
        )
        self.edge_types = edge_types
        self.num_hops = num_hops
        self.max_degree = max_degree

    def query(self, roots: np.ndarray) -> MiniBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        hop_ids = [roots]
        hop_masks = [roots != DEFAULT_ID]
        blocks = []
        cur = roots
        for _ in range(self.num_hops):
            nbr, w, _, mask, _ = self.graph.get_full_neighbor(
                cur, self.edge_types, max_degree=self.max_degree
            )
            blocks.append(fanout_block(len(cur), self.max_degree, w, mask))
            cur = nbr.reshape(-1)
            hop_ids.append(cur)
            hop_masks.append(mask.reshape(-1))
        feats = tuple(self.node_feats(ids) for ids in hop_ids)
        return MiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )
