"""Sampled-fanout dataflow (GraphSAGE) — SageDataFlow parity
(tf_euler/python/dataflow/sage_dataflow.py:35-50) with padded static shapes.
"""

from __future__ import annotations

import numpy as np

import ml_dtypes

from euler_tpu.dataflow.base import DataFlow, MiniBatch, fanout_block
from euler_tpu.graph.store import DEFAULT_ID, lean_wire_ok

_BF16 = np.dtype(ml_dtypes.bfloat16)


class SageDataFlow(DataFlow):
    def __init__(
        self,
        graph,
        feature_names,
        edge_types=None,
        fanouts=(10, 10),
        label_feature=None,
        label_dim=None,
        rng=None,
        feature_mode="dense",
        lazy_blocks: bool = False,
        lean: bool = False,
    ):
        """lean=True minimizes wire bytes on the fused rows path: ships only
        int32 feature rows + labels, with edge ids, masks, and (uniform)
        weights rebuilt on device by hydrate_blocks. Requires
        feature_mode="rows"; hop_ids are omitted (no id-embedding models).

        Weighted graphs stay lean too (VERDICT r3 #5): when the graph's
        edge weights are not all 1.0, the flow ships bf16 weights next to
        the int32 rows (~1.5x lean bytes) instead of downgrading to the
        ~6x full wire the way the reference's REMOTE op never has to
        (remote_op.cc:60-120 serves weighted graphs at full speed). The
        mode is decided once at construction so every batch of a run has
        the same pytree structure."""
        if lean and feature_mode != "rows":
            raise ValueError("lean=True requires feature_mode='rows'")
        super().__init__(
            graph, feature_names, label_feature, label_dim, rng, feature_mode
        )
        self.edge_types = edge_types
        self.fanouts = list(fanouts)
        self.lazy_blocks = lazy_blocks or lean
        self.lean = lean
        # set the first time a batch violates the lean assumptions; from
        # then on every batch ships full arrays so pytree structure stays
        # stable across a run (stack_batches / scan-dispatch requirement)
        self._lean_off = False
        # weighted-lean: ship bf16 edge weights when the graph is weighted
        self._lean_w = False
        if lean:
            probe = getattr(graph, "unit_edge_weights", None)
            try:
                self._lean_w = probe is not None and not probe(edge_types)
            except Exception:
                self._lean_w = False  # can't tell → unit-lean with its
                # per-batch lean_wire_ok guard (weighted batches downgrade)

    @property
    def num_hops(self) -> int:
        return len(self.fanouts)

    def minibatch(self, batch_size: int, node_type: int = -1) -> MiniBatch:
        """One training minibatch. Against a remote cluster this is a
        SINGLE RPC — the server samples roots, runs the fused fanout, and
        fetches labels next to the data (SampleFanoutWithFeature parity);
        in-process graphs fall back to sample_node + query(roots), which
        is already zero-copy there."""
        remote = getattr(self.graph, "sage_minibatch", None)
        if remote is not None and self.feature_mode == "rows":
            res = remote(
                batch_size,
                self.edge_types,
                self.fanouts,
                label=self.label_feature,
                node_type=node_type,
                rng=self.rng,
                lean=self.lean and not self._lean_off,
            )
            if res is not None:
                return self._from_remote(res)
        roots = self.graph.sample_node(batch_size, node_type, rng=self.rng)
        return self.query(roots)

    def minibatch_async(self, batch_size: int, node_type: int = -1):
        """Pipelined minibatch: returns a Future of a MiniBatch with up to
        EULER_TPU_INFLIGHT requests overlapped per shard, or None when the
        graph has no async surface (in-process) — callers then use the
        sync minibatch(). Decode + MiniBatch assembly run in the RPC
        worker thread (pure numpy; the only shared write is the sticky
        _lean_off downgrade flag, a benign bool)."""
        submit = getattr(self.graph, "sage_minibatch_async", None)
        if submit is None or self.feature_mode != "rows":
            return None
        fut = submit(
            batch_size,
            self.edge_types,
            self.fanouts,
            label=self.label_feature,
            node_type=node_type,
            rng=self.rng,
            lean=self.lean and not self._lean_off,
        )
        if fut is None:
            return None

        import concurrent.futures

        out: concurrent.futures.Future = concurrent.futures.Future()

        def chain(f):
            try:
                out.set_result(self._from_remote(f.result()))
            except BaseException as e:  # propagate to the consumer
                out.set_exception(e)

        fut.add_done_callback(chain)
        return out

    def _from_remote(self, res: dict) -> MiniBatch:
        roots = np.asarray(res["roots"], np.uint64)
        if res["lean"]:
            widths = [len(roots)]
            for k in self.fanouts:
                widths.append(widths[-1] * k)
            offs = np.cumsum([0] + widths)
            feats = tuple(
                res["feats"][offs[i] : offs[i + 1]]
                for i in range(len(widths))
            )
            # weighted-lean: the server shipped bf16 weights, concat over
            # hops 1.. (same widths as the non-root feats)
            w = res.get("w")
            w_hops = (
                None
                if w is None
                else [
                    w[offs[i] - offs[1] : offs[i + 1] - offs[1]]
                    for i in range(1, len(widths))
                ]
            )
            blocks = []
            width = len(roots)
            for h, k in enumerate(self.fanouts):
                blocks.append(
                    fanout_block(
                        width, k,
                        None if w_hops is None else w_hops[h], None,
                        lazy=True, ship_w=w_hops is not None,
                        ship_mask=False,
                        w_dtype=None if w_hops is None else w_hops[h].dtype,
                    )
                )
                width *= k
            return MiniBatch(
                feats=feats,
                masks=None,
                blocks=tuple(blocks),
                root_idx=roots.astype(np.int64).astype(np.int32),
                labels=res["labels"],
                hop_ids=None,
            )
        if self.lean:
            # the server found a lean violation in this batch; downgrade
            # stays sticky for the same structure-stability reasons as the
            # local path
            self._lean_off = True
        hop_ids, hop_w, _, hop_masks, hop_rows = res["hops"]
        return self._from_fused(
            roots, hop_ids, hop_w, hop_masks, hop_rows,
            labels=res["labels"], have_labels=True,
        )

    def query(self, roots: np.ndarray) -> MiniBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        fused = getattr(self.graph, "fanout_with_rows", None)
        res = (
            fused(roots, self.edge_types, self.fanouts, rng=self.rng)
            if fused is not None
            else None
        )
        if res is not None:
            # fused path: one native-engine call yields every hop's ids,
            # weights, masks AND feature-cache rows
            hop_ids, hop_w, _, hop_masks, hop_rows = res
            return self._from_fused(roots, hop_ids, hop_w, hop_masks, hop_rows)
        # no fused rows → nothing to derive lean masks from: full arrays
        hop_ids = [roots]
        hop_masks = [roots != DEFAULT_ID]
        blocks = []
        cur = roots
        for k in self.fanouts:
            nbr, w, _, mask, _ = self.graph.sample_neighbor(
                cur, self.edge_types, k, rng=self.rng
            )
            blocks.append(
                fanout_block(len(cur), k, w, mask, lazy=self.lazy_blocks)
            )
            cur = nbr.reshape(-1)
            hop_ids.append(cur)
            hop_masks.append(mask.reshape(-1))
        # padded slots hold DEFAULT_ID → feature fetch returns zeros;
        # cross-hop dedup: hop 2 re-cites hop 1's hot nodes, so the
        # unique set — not every duplicate slot — goes to the wire
        feats = self.node_feats_hops(hop_ids)
        return MiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=None
            if self.lean
            else tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )

    def _from_fused(
        self,
        roots: np.ndarray,
        hop_ids,
        hop_w,
        hop_masks,
        hop_rows,
        labels=None,
        have_labels: bool = False,
    ) -> MiniBatch:
        # hop-0 validity matches the fallback path (any non-default id
        # counts, even if absent from the store — its features are zero)
        hop_masks = [roots != DEFAULT_ID] + list(hop_masks[1:])
        lean = self.lean and not self._lean_off
        if lean:
            # a batch violating a lean invariant (lean_wire_ok) would
            # silently train on wrong values after on-device hydration, so
            # it ships full arrays instead. The downgrade is STICKY: mixed
            # lean/full batches have different pytree structure, which
            # breaks steps_per_call stacking and forces jit recompiles.
            # Weighted graphs (self._lean_w) skip the unit-weight check
            # and ship bf16 weights instead (weighted-lean wire).
            lean = lean_wire_ok(
                roots, hop_w, hop_masks, hop_rows,
                require_unit_w=not self._lean_w,
            )
            if not lean:
                self._lean_off = True
        lean_w = lean and self._lean_w
        blocks = []
        width = len(roots)
        for k, w, mask in zip(self.fanouts, hop_w[1:], hop_masks[1:]):
            blocks.append(
                fanout_block(
                    width, k, w, mask,
                    lazy=self.lazy_blocks,
                    ship_w=(not lean) or lean_w,
                    ship_mask=not lean,
                    w_dtype=_BF16 if lean_w else np.float32,
                )
            )
            width *= k
        if self.feature_mode == "rows":
            feats = tuple(
                np.where(r >= 0, r + 1, 0).astype(np.int32)
                for r in hop_rows
            )
        elif self.feature_names and hasattr(
            self.graph, "get_dense_by_rows"
        ):
            # reuse the rows the fanout already resolved — no second
            # per-id lookup pass (the facade splits global rows back to
            # their owner shards on partitioned graphs). Rows dedup
            # across hops before the wire: a hot node's feature row
            # ships (or cache-misses) once per batch, not once per slot.
            from euler_tpu.dataflow.base import gather_unique

            try:
                feats = tuple(gather_unique(
                    hop_rows,
                    lambda u: self.graph.get_dense_by_rows(
                        u, self.feature_names
                    ),
                ))
            except RuntimeError as e:
                # capability gap only (older server / no row space):
                # fall back to per-id fetch; real failures must surface
                if "unknown op" in str(e) or "num_nodes" in str(e):
                    feats = self.node_feats_hops(hop_ids)
                else:
                    raise
        else:
            feats = self.node_feats_hops(hop_ids)
        return MiniBatch(
            feats=feats,
            masks=None if lean else tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=labels if have_labels else self.labels_of(roots),
            # a lean-configured flow never ships hop_ids, even for
            # downgraded batches — so a downgraded batch has the same
            # pytree structure as an upgrade_lean_host()-hydrated lean one
            # (steps_per_call windows can mix them)
            hop_ids=None
            if self.lean
            else tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )


class FullNeighborDataFlow(DataFlow):
    """Full-neighbor dataflow (GCNDataFlow parity) with a degree cap.

    Every hop expands each node to its full (capped) neighbor list; the cap
    keeps shapes static — the padded analog of gcn_dataflow.py.
    """

    def __init__(
        self,
        graph,
        feature_names,
        edge_types=None,
        num_hops=2,
        max_degree=32,
        label_feature=None,
        label_dim=None,
        rng=None,
        feature_mode="dense",
        gcn_norm: bool = False,
    ):
        """gcn_norm=True attaches each hop's TRUE graph degrees to the
        blocks (src_deg/dst_deg), so GCNConv runs the exact symmetric
        normalization instead of the in-batch approximation."""
        super().__init__(
            graph, feature_names, label_feature, label_dim, rng, feature_mode
        )
        self.edge_types = edge_types
        self.num_hops = num_hops
        self.max_degree = max_degree
        self.gcn_norm = gcn_norm

    def query(self, roots: np.ndarray) -> MiniBatch:
        roots = np.asarray(roots, dtype=np.uint64)
        from euler_tpu.query.plan import is_remote_graph, plan_mode

        if is_remote_graph(self.graph) and plan_mode() != "off":
            # remote cluster: ship the WHOLE query (every hop's capped
            # expansion + features + degrees + labels) as one sub-plan
            # per owner shard instead of ~(3·hops+2)×P per-op rounds
            return self._query_plan(roots)
        hop_ids = [roots]
        hop_masks = [roots != DEFAULT_ID]
        blocks = []
        cur = roots
        for _ in range(self.num_hops):
            nbr, w, _, mask, _ = self.graph.get_full_neighbor(
                cur, self.edge_types, max_degree=self.max_degree
            )
            blocks.append(fanout_block(len(cur), self.max_degree, w, mask))
            cur = nbr.reshape(-1)
            hop_ids.append(cur)
            hop_masks.append(mask.reshape(-1))
        if self.gcn_norm:
            # cross-hop dedup: every hop re-cites its parents, so the
            # true-degree fetch ships each unique id once
            from euler_tpu.dataflow.base import gather_unique

            degs = gather_unique(
                hop_ids,
                lambda u: np.asarray(
                    self.graph.degree_sum(u, self.edge_types), np.float32
                ),
            )
            blocks = [
                b.replace(dst_deg=degs[h], src_deg=degs[h + 1])
                for h, b in enumerate(blocks)
            ]
        feats = self.node_feats_hops(hop_ids)
        return MiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=self.labels_of(roots),
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )

    def _query_plan(self, roots: np.ndarray) -> MiniBatch:
        """Planner-routed remote query: one exec_plan RPC per owner shard
        covers every hop, feature fetch, degree fetch, and the labels."""
        from euler_tpu.query.plan import (
            full_neighbor_plan,
            plan_mode,
            run_plan,
        )

        rows_mode = self.feature_mode == "rows"
        # fully-cached roots skip the plan's hop-0 feature step: the
        # server neither gathers nor ships rows the client will fill
        # from its read cache (bit-identical bytes) below
        skip_root_feats = False
        if not rows_mode and self.feature_names:
            from euler_tpu.distributed.cache import dense_coverage

            skip_root_feats = dense_coverage(
                self.graph, roots, self.feature_names
            )
        plan = full_neighbor_plan(
            self.edge_types,
            self.num_hops,
            self.max_degree,
            feature_names=self.feature_names if not rows_mode else None,
            label=self.label_feature,
            rows=rows_mode,
            degrees=self.gcn_norm,
            root_features=not skip_root_feats,
        )
        seed = int(self.rng.integers(0, 2**63 - 1))
        # epoch stamps for the write-back below, captured BEFORE the
        # RPC: a publish landing while the plan is in flight must void
        # the seeding (insert-time epoch check), not let pre-publish
        # rows re-enter the cache stamped as the new epoch
        seed_epochs = None
        if not rows_mode and self.feature_names:
            from euler_tpu.distributed.cache import snapshot_epochs

            seed_epochs = snapshot_epochs(self.graph)
        res = run_plan(
            self.graph, plan, roots, seed, fused=plan_mode() == "fused"
        )
        hop_ids = [roots]
        hop_masks = [roots != DEFAULT_ID]
        blocks = []
        width = len(roots)
        for h in range(self.num_hops):
            nbr, w, _, mask = res[f"__nb{h + 1}"]
            blocks.append(fanout_block(width, self.max_degree, w, mask))
            hop_ids.append(nbr.reshape(-1))
            hop_masks.append(mask.reshape(-1))
            width *= self.max_degree
        if self.gcn_norm:
            degs = [
                np.asarray(res[f"__deg{h}"], np.float32)
                for h in range(self.num_hops + 1)
            ]
            blocks = [
                b.replace(dst_deg=degs[h], src_deg=degs[h + 1])
                for h, b in enumerate(blocks)
            ]
        if rows_mode:
            hop_rows = res["__hops"][4]
            feats = tuple(
                np.where(r >= 0, r + 1, 0).astype(np.int32) for r in hop_rows
            )
        elif self.feature_names:
            feats = tuple(
                # hop 0 skipped on the wire → every row is a cache hit
                self.graph.get_dense_feature(roots, self.feature_names)
                if (h == 0 and skip_root_feats)
                else res[f"__f{h}"]
                for h in range(self.num_hops + 1)
            )
            # write-back: rows that arrived inside the fused response
            # seed the read cache so the NEXT plan over these (hot) ids
            # skips its feature steps and direct fetches hit
            from euler_tpu.distributed.cache import seed_dense_rows

            for h in range(self.num_hops + 1):
                if h == 0 and skip_root_feats:
                    continue  # those rows came FROM the cache
                seed_dense_rows(
                    self.graph, hop_ids[h], self.feature_names, feats[h],
                    epochs=seed_epochs,
                )
        else:
            feats = tuple(
                np.zeros((len(ids), 0), np.float32) for ids in hop_ids
            )
        return MiniBatch(
            feats=feats,
            masks=tuple(hop_masks),
            blocks=tuple(blocks),
            root_idx=roots.astype(np.int64).astype(np.int32),
            labels=res.get("__labels") if self.label_feature else None,
            hop_ids=tuple(
                ids.astype(np.int64).astype(np.int32) for ids in hop_ids
            ),
        )
