from euler_tpu.dataflow.base import Block, DataFlow, MiniBatch, fanout_block  # noqa: F401
from euler_tpu.dataflow.device import (  # noqa: F401
    DeviceDgiFlow,
    DeviceEdgeFlow,
    DeviceGaeFlow,
    DeviceGraphTables,
    DeviceKGFlow,
    DeviceLayerwiseFlow,
    DeviceRelationFlow,
    DeviceSageFlow,
    DeviceUnsupSageFlow,
    DeviceWalkFlow,
    DeviceWholeGraphFlow,
)
from euler_tpu.dataflow.sage import FullNeighborDataFlow, SageDataFlow  # noqa: F401
from euler_tpu.dataflow.walk import gen_pair  # noqa: F401
from euler_tpu.dataflow.whole import (  # noqa: F401
    FullGraphFlow,
    GraphBatch,
    WholeGraphDataFlow,
    graph_label_batches,
)
from euler_tpu.dataflow.layerwise import LayerwiseBatch, LayerwiseDataFlow  # noqa: F401
from euler_tpu.dataflow.relation import RelationDataFlow, RelMiniBatch  # noqa: F401
