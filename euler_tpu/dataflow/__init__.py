from euler_tpu.dataflow.base import Block, DataFlow, MiniBatch, fanout_block  # noqa: F401
from euler_tpu.dataflow.sage import FullNeighborDataFlow, SageDataFlow  # noqa: F401
