"""Random-walk skip-gram pair generation (walk_ops.py:26-45 /
gen_pair_op.cc:16-70 parity).

`gen_pair` slides a [left_win, right_win] window over each walk and emits
(src, ctx) id pairs, skipping padded (DEFAULT_ID) slots — all vectorized
host-side numpy.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.graph.store import DEFAULT_ID


def gen_pair(
    walks: np.ndarray, left_win: int = 1, right_win: int = 1
) -> np.ndarray:
    """walks u64 [n, L] → pairs u64 [n * L * (left+right), 2] with mask.

    Returns (pairs, mask): fixed shape for a given (L, windows), so the
    downstream embedding step keeps a static batch size.
    """
    walks = np.asarray(walks, dtype=np.uint64)
    n, length = walks.shape
    srcs, ctxs = [], []
    for off in range(-left_win, right_win + 1):
        if off == 0:
            continue
        lo, hi = max(0, -off), min(length, length - off)
        src = walks[:, lo:hi]
        ctx = walks[:, lo + off : hi + off]
        pad = length - (hi - lo)
        if pad:
            fill = np.full((n, pad), DEFAULT_ID, dtype=np.uint64)
            src = np.concatenate([src, fill], axis=1)
            ctx = np.concatenate([ctx, fill], axis=1)
        srcs.append(src)
        ctxs.append(ctx)
    src = np.concatenate(srcs, axis=1).reshape(-1)
    ctx = np.concatenate(ctxs, axis=1).reshape(-1)
    pairs = np.stack([src, ctx], axis=1)
    mask = (src != DEFAULT_ID) & (ctx != DEFAULT_ID)
    return pairs, mask
