"""GNN networks over MiniBatch blocks.

`GNNNet` mirrors the reference's `BaseGNNNet.__call__` loop
(tf_euler/python/mp_utils/base_gnn.py:74-92): layer l transforms hops
[0, H-l) using one shared conv per layer, consuming one block per step, so
after H layers hop 0 carries the final root embeddings. `JKGNNNet` adds
jumping-knowledge concatenation (base_gnn.py:94-139).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.dataflow.base import MiniBatch
from euler_tpu.layers import get_conv


class GNNNet(nn.Module):
    """Stack of shared-per-layer convs over a fanout MiniBatch.

    conv: layer name from euler_tpu.layers.CONVS
    dims: output dim per layer; len(dims) must equal len(batch.blocks)
    remat: rematerialize each layer's forward on the backward pass
      (jax.checkpoint / nn.remat) — a fanout batch's activations scale as
      Σ_l B·Πk_i·F per layer, which dominates HBM for deep stacks or wide
      fanouts; remat trades one extra forward FLOP pass for dropping them,
      the standard TPU memory lever. Numerics are identical (asserted in
      tests/test_training.py).
    """

    conv: str
    dims: Sequence[int]
    activation: str = "relu"
    conv_kwargs: dict | None = None
    remat: bool = False

    def setup(self):
        cls = get_conv(self.conv)
        if self.remat:
            cls = nn.remat(cls, static_argnums=())
        kwargs = dict(self.conv_kwargs or {})
        self.convs = [cls(out_dim=d, **kwargs) for d in self.dims]

    def __call__(self, batch: MiniBatch) -> jnp.ndarray:
        num_hops = len(batch.blocks)
        assert len(self.dims) == num_hops, (
            f"dims {self.dims} must match hop count {num_hops}"
        )
        act = getattr(nn, self.activation)
        xs = list(batch.feats)
        for layer in range(num_hops):
            conv = self.convs[layer]
            last = layer == num_hops - 1
            new_xs = []
            for hop in range(num_hops - layer):
                h = conv(xs[hop], xs[hop + 1], batch.blocks[hop])
                if not last:
                    h = act(h)
                # zero out padded node slots so garbage never propagates
                h = h * batch.masks[hop][: h.shape[0], None]
                new_xs.append(h)
            xs = new_xs
        return xs[0]


class JKGNNNet(nn.Module):
    """Jumping-knowledge variant: concatenates every layer's hop-0 output
    (base_gnn.py:94-139) then projects."""

    conv: str
    dims: Sequence[int]
    out_dim: int
    activation: str = "relu"

    def setup(self):
        cls = get_conv(self.conv)
        self.convs = [cls(out_dim=d) for d in self.dims]
        self.proj = nn.Dense(self.out_dim)

    def __call__(self, batch: MiniBatch) -> jnp.ndarray:
        num_hops = len(batch.blocks)
        act = getattr(nn, self.activation)
        xs = list(batch.feats)
        collected = []
        for layer in range(num_hops):
            conv = self.convs[layer]
            new_xs = []
            for hop in range(num_hops - layer):
                h = conv(xs[hop], xs[hop + 1], batch.blocks[hop])
                h = act(h)
                h = h * batch.masks[hop][: h.shape[0], None]
                new_xs.append(h)
            xs = new_xs
            collected.append(xs[0])
        return self.proj(jnp.concatenate(collected, axis=-1))
