"""Feature encoders (tf_euler/python/utils/encoders.py:32-171 parity).

`ShallowEncoder` combines an id-embedding lookup, a dense-feature projection,
and sparse-feature embeddings — the input stage of DeepWalk/LINE/TransX and
the GNN example models. The id table is declared with
`nn.with_partitioning` over the "model" mesh axis, so under a
`jax.sharding.Mesh` the table rows shard across devices and XLA inserts the
gather collectives (the TPU-native version of the reference's
parameter-server-partitioned embedding variables, layers.py:119-171).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.ops import gather


class Embedding(nn.Module):
    """Sharded id-embedding table: rows partitioned over the 'model' axis.

    row_init overrides the default normal(0.02) row initializer — KG
    models use this to start relation projections at identity/zero so
    TransR/D begin as TransE (the published training recipe). (Named
    row_init, not init: flax reserves Module.init.)"""

    vocab: int
    dim: int
    partitioned: bool = True
    row_init: object = None

    @nn.compact
    def __call__(self, ids: jnp.ndarray) -> jnp.ndarray:
        init = self.row_init or nn.initializers.normal(stddev=0.02)
        if self.partitioned:
            init = nn.with_partitioning(init, ("model", None))
        # rows padded to a 128 multiple: shardable by any practical model-axis
        # size and aligned to the TPU lane tile
        rows = -(-self.vocab // 128) * 128
        table = self.param("table", init, (rows, self.dim), jnp.float32)
        return gather(jnp.asarray(table), jnp.clip(ids, 0, self.vocab - 1))


class SparseEmbedding(nn.Module):
    """Masked bag-of-ids embedding (layers.py SparseEmbedding parity).

    ids: int32[..., L] hashed into the table; mask: bool[..., L].
    combiner 'mean' | 'sum'.
    """

    vocab: int
    dim: int
    combiner: str = "mean"

    @nn.compact
    def __call__(self, ids, mask):
        emb = Embedding(self.vocab, self.dim, partitioned=True)(
            ids % self.vocab
        )
        m = mask.astype(jnp.float32)[..., None]
        total = jnp.sum(emb * m, axis=-2)
        if self.combiner == "sum":
            return total
        count = jnp.maximum(jnp.sum(m, axis=-2), 1.0)
        return total / count


class ShallowEncoder(nn.Module):
    """id-emb ⊕ dense-proj ⊕ sparse-emb combiner (encoders.py:32-171)."""

    dim: int
    max_id: int = 0  # 0 disables the id embedding
    sparse_vocabs: Sequence[int] = ()
    combiner: str = "add"  # add | concat
    use_feature_proj: bool = True

    @nn.compact
    def __call__(self, ids=None, dense=None, sparse=None):
        """ids: int32[...]; dense: f32[..., F]; sparse: [(ids, mask), ...]."""
        parts = []
        if self.max_id > 0 and ids is not None:
            parts.append(Embedding(self.max_id + 1, self.dim)(ids))
        if dense is not None and dense.shape[-1] > 0:
            parts.append(
                nn.Dense(self.dim)(dense) if self.use_feature_proj else dense
            )
        for vocab, (sids, smask) in zip(self.sparse_vocabs, sparse or ()):
            parts.append(SparseEmbedding(vocab, self.dim)(sids, smask))
        if not parts:
            raise ValueError("ShallowEncoder needs at least one input kind")
        if self.combiner == "concat":
            return jnp.concatenate(parts, axis=-1)
        out = parts[0]
        for p in parts[1:]:
            out = out + p
        return out
