from euler_tpu.nn import metrics  # noqa: F401
from euler_tpu.nn.base_gnn import GNNNet, JKGNNNet  # noqa: F401
from euler_tpu.nn.heads import SuperviseModel, UnsuperviseModel  # noqa: F401
