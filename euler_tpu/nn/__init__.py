from euler_tpu.nn import metrics  # noqa: F401
from euler_tpu.nn.embedding import (  # noqa: F401
    embedding_add,
    embedding_moving_average,
    embedding_update,
    partitioned_lookup,
    partitioned_update,
)
from euler_tpu.nn.base_gnn import GNNNet, JKGNNNet  # noqa: F401
from euler_tpu.nn.heads import SuperviseModel, UnsuperviseModel  # noqa: F401
