"""Neighborhood aggregators over padded [B, K, F] grids
(tf_euler/python/utils/aggregators.py + sparse_aggregators.py parity):
mean / meanpool / maxpool / gcn / attention.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Aggregator(nn.Module):
    dim: int

    def masked(self, nbr, mask):
        return nbr * mask.astype(nbr.dtype)[..., None]


class MeanAggregator(Aggregator):
    @nn.compact
    def __call__(self, self_x, nbr, mask):
        m = mask.astype(jnp.float32)[..., None]
        mean = jnp.sum(nbr * m, axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
        return nn.relu(
            nn.Dense(self.dim)(self_x) + nn.Dense(self.dim, use_bias=False)(mean)
        )


class GCNAggregator(Aggregator):
    @nn.compact
    def __call__(self, self_x, nbr, mask):
        m = mask.astype(jnp.float32)[..., None]
        total = jnp.sum(nbr * m, axis=1) + self_x
        mean = total / (m.sum(axis=1) + 1.0)
        return nn.relu(nn.Dense(self.dim)(mean))


class MeanPoolAggregator(Aggregator):
    @nn.compact
    def __call__(self, self_x, nbr, mask):
        h = nn.relu(nn.Dense(self.dim)(nbr))
        m = mask.astype(jnp.float32)[..., None]
        pooled = jnp.sum(h * m, axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
        return nn.relu(
            nn.Dense(self.dim)(self_x) + nn.Dense(self.dim, use_bias=False)(pooled)
        )


class MaxPoolAggregator(Aggregator):
    @nn.compact
    def __call__(self, self_x, nbr, mask):
        h = nn.relu(nn.Dense(self.dim)(nbr))
        neg = jnp.finfo(h.dtype).min
        pooled = jnp.max(jnp.where(mask[..., None], h, neg), axis=1)
        pooled = jnp.where(mask.any(axis=1)[:, None], pooled, 0.0)
        return nn.relu(
            nn.Dense(self.dim)(self_x) + nn.Dense(self.dim, use_bias=False)(pooled)
        )


class AttentionAggregator(Aggregator):
    @nn.compact
    def __call__(self, self_x, nbr, mask):
        q = nn.Dense(self.dim)(self_x)  # [B, D]
        k = nn.Dense(self.dim)(nbr)  # [B, K, D]
        e = jnp.einsum("bd,bkd->bk", q, k) / jnp.sqrt(float(self.dim))
        e = jnp.where(mask, e, jnp.finfo(e.dtype).min)
        alpha = nn.softmax(e, axis=1)
        alpha = jnp.where(mask, alpha, 0.0)
        out = jnp.einsum("bk,bkd->bd", alpha, k)
        return nn.relu(q + out)


AGGREGATORS = {
    "mean": MeanAggregator,
    "gcn": GCNAggregator,
    "meanpool": MeanPoolAggregator,
    "maxpool": MaxPoolAggregator,
    "attention": AttentionAggregator,
}


def get_aggregator(name: str):
    if name not in AGGREGATORS:
        raise KeyError(f"unknown aggregator {name!r}; have {sorted(AGGREGATORS)}")
    return AGGREGATORS[name]
