"""Metrics (tf_euler/python/utils/metrics.py:23-97 parity): accuracy, f1,
auc, mrr, mr, hit@k — all as pure jittable functions."""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(labels, predictions) -> jnp.ndarray:
    """Exact-match accuracy over hard predictions."""
    return jnp.mean((predictions == labels).astype(jnp.float32))


def micro_f1(labels, logits, threshold: float = 0.0) -> jnp.ndarray:
    """Micro-averaged F1 for multi-label sigmoid heads (metrics.py f1)."""
    preds = (logits > threshold).astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    tp = jnp.sum(preds * labels)
    fp = jnp.sum(preds * (1 - labels))
    fn = jnp.sum((1 - preds) * labels)
    return 2 * tp / jnp.maximum(2 * tp + fp + fn, 1e-9)


def auc(labels, scores) -> jnp.ndarray:
    """Pairwise-ranking AUC (probability a positive outranks a negative)."""
    labels = labels.reshape(-1).astype(jnp.float32)
    scores = scores.reshape(-1)
    pos = labels > 0.5
    diff = scores[:, None] - scores[None, :]
    pair = pos[:, None] & ~pos[None, :]
    wins = jnp.where(pair, (diff > 0) + 0.5 * (diff == 0), 0.0)
    return jnp.sum(wins) / jnp.maximum(jnp.sum(pair), 1)


def ranks_from_scores(pos_scores, neg_scores) -> jnp.ndarray:
    """Rank of each positive among its negatives (1-based).

    pos_scores: [B] ; neg_scores: [B, N].
    """
    better = jnp.sum((neg_scores > pos_scores[:, None]).astype(jnp.float32), -1)
    ties = jnp.sum((neg_scores == pos_scores[:, None]).astype(jnp.float32), -1)
    return 1.0 + better + 0.5 * ties


def mrr(pos_scores, neg_scores) -> jnp.ndarray:
    return jnp.mean(1.0 / ranks_from_scores(pos_scores, neg_scores))


def mean_rank(pos_scores, neg_scores) -> jnp.ndarray:
    return jnp.mean(ranks_from_scores(pos_scores, neg_scores))


def hit_at_k(pos_scores, neg_scores, k: int) -> jnp.ndarray:
    return jnp.mean(
        (ranks_from_scores(pos_scores, neg_scores) <= k).astype(jnp.float32)
    )


METRICS = {
    "acc": accuracy,
    "f1": micro_f1,
    "auc": auc,
    "mrr": mrr,
    "mr": mean_rank,
}
