"""Task heads: supervised and unsupervised (negative-sampling) models.

Mirrors the reference's model contract (tf_euler/python/mp_utils/base.py:24-95):
a model call returns (embedding, loss, metric_name, metric). `SuperviseModel`
is sigmoid cross-entropy + micro-F1 (base.py:24-49); `UnsuperviseModel` embeds
(src, pos, negs) with a shared GNN and optimizes sampled-softmax
cross-entropy, reporting MRR (base.py:52-95).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import optax

from euler_tpu.dataflow.base import MiniBatch
from euler_tpu.nn.base_gnn import GNNNet
from euler_tpu.nn.metrics import micro_f1, mrr


class SuperviseModel(nn.Module):
    conv: str
    dims: Sequence[int]
    label_dim: int
    conv_kwargs: dict | None = None
    remat: bool = False  # rematerialize conv layers (GNNNet.remat)

    def setup(self):
        self.gnn = GNNNet(
            conv=self.conv, dims=self.dims, conv_kwargs=self.conv_kwargs,
            remat=self.remat,
        )
        self.out = nn.Dense(self.label_dim)

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        return self.gnn(batch)

    def __call__(self, batch: MiniBatch):
        emb = self.embed(batch)
        if batch.target_idx is not None:
            # whole-graph flows: only the target rows carry loss/metric
            emb = emb[batch.target_idx]
        logits = self.out(emb)
        labels = batch.labels
        loss = optax.sigmoid_binary_cross_entropy(logits, labels)
        loss = jnp.mean(jnp.sum(loss, axis=-1))
        return emb, loss, "f1", micro_f1(labels, logits)


class UnsuperviseModel(nn.Module):
    """src/pos/neg contrastive head over a shared GNN encoder."""

    conv: str
    dims: Sequence[int]
    conv_kwargs: dict | None = None
    temperature: float = 1.0
    remat: bool = False  # rematerialize conv layers (GNNNet.remat)

    def setup(self):
        self.gnn = GNNNet(
            conv=self.conv, dims=self.dims, conv_kwargs=self.conv_kwargs,
            remat=self.remat,
        )

    def embed(self, batch: MiniBatch) -> jnp.ndarray:
        return self.gnn(batch)

    def __call__(self, src: MiniBatch, pos: MiniBatch, negs: MiniBatch):
        """negs hold B*N roots (N negatives per source)."""
        e_src = self.embed(src)  # [B, D]
        e_pos = self.embed(pos)  # [B, D]
        e_neg = self.embed(negs)  # [B*N, D]
        b, d = e_src.shape
        e_neg = e_neg.reshape(b, -1, d)
        pos_logit = jnp.sum(e_src * e_pos, axis=-1) / self.temperature  # [B]
        neg_logit = (
            jnp.einsum("bd,bnd->bn", e_src, e_neg) / self.temperature
        )  # [B, N]
        logits = jnp.concatenate([pos_logit[:, None], neg_logit], axis=1)
        labels = jnp.zeros(b, dtype=jnp.int32)  # positive is column 0
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        )
        return e_src, loss, "mrr", mrr(pos_logit, neg_logit)
