"""Partial updates of (partitioned) embedding tables.

The reference scatters updates into PS-partitioned embedding variables with
a mod partition strategy (tf_euler/python/utils/embedding.py:24-90:
`embedding_update`/`embedding_add` over `PartitionedVariable`). The JAX
equivalents are functional: `.at[rows]` scatters on a device table — under
jit with donated buffers they update in place, and on a mesh-sharded table
XLA routes the scatter through the owning shards. The mod-partitioned
list-of-tables form is kept for host-offloaded tables too big for one HBM.
"""

from __future__ import annotations

import jax.numpy as jnp


def embedding_update(table, ids, values):
    """rows[ids] = values (tf.scatter_update parity)."""
    return table.at[ids].set(values)


def embedding_add(table, ids, values):
    """rows[ids] += values (tf.scatter_add parity)."""
    return table.at[ids].add(values)


def embedding_moving_average(table, ids, values, momentum: float):
    """rows[ids] = m*rows[ids] + (1-m)*values (history-embedding refresh)."""
    old = table[ids]
    return table.at[ids].set(momentum * old + (1.0 - momentum) * values)


def _mod_partition(ids, num_parts: int):
    """mod strategy: part = id % P, local row = id // P."""
    ids = jnp.asarray(ids)
    return ids % num_parts, ids // num_parts


def partitioned_lookup(tables: list, ids):
    """Gather rows from mod-partitioned tables (embedding_lookup parity).

    Each table p holds rows {id : id % P == p} at local row id // P. The
    gather touches every partition with masked scatters so shapes stay
    static under jit.
    """
    part, local = _mod_partition(ids, len(tables))
    out = jnp.zeros(ids.shape + tables[0].shape[1:], tables[0].dtype)
    for p, t in enumerate(tables):
        sel = part == p
        rows = jnp.where(sel, local, 0)
        out = jnp.where(sel[..., None], t[rows], out)
    return out


def partitioned_update(
    tables: list, ids, values, func=embedding_update, momentum: float = 0.9
):
    """Scatter `values` into mod-partitioned tables; returns new tables.

    func is embedding_update, embedding_add, or embedding_moving_average
    (the reference's tf.scatter_update / tf.scatter_add choice; `momentum`
    applies to the moving-average form only). Any other func is an error —
    a silent fall-through to overwrite semantics would corrupt the table.
    Duplicate ids within one call have undefined precedence (the
    reference's tf.scatter_update shares that caveat).
    """
    if func not in (embedding_update, embedding_add, embedding_moving_average):
        raise ValueError(
            "partitioned_update supports embedding_update / embedding_add /"
            f" embedding_moving_average, got {func!r}"
        )
    part, local = _mod_partition(ids, len(tables))
    out = []
    for p, t in enumerate(tables):
        sel = part == p
        rows = jnp.where(sel, local, 0)
        if func is embedding_add:
            delta = jnp.where(sel[..., None], values, 0)
        elif func is embedding_moving_average:
            # new = m*old + (1-m)*v  →  delta = (1-m)*(v - old)
            delta = jnp.where(
                sel[..., None], (1.0 - momentum) * (values - t[rows]), 0
            )
        else:
            # set as an add of (value - current): unselected ids collapse to
            # row 0 with delta 0, so scatter collisions there are harmless
            delta = jnp.where(sel[..., None], values - t[rows], 0)
        out.append(t.at[rows].add(delta))
    return out
