"""Graph-level readouts (tf_euler/python/graph_pool parity):
segment pooling (add/mean/max), attention pooling (scatter_softmax gating,
attention_pool.py:36-51), and Set2Set (LSTM attention readout)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.ops import gather, scatter, scatter_softmax


class Pooling(nn.Module):
    """Plain segment pooling over graph ids. op ∈ {add, mean, max}."""

    op: str = "mean"

    @nn.compact
    def __call__(self, x, graph_ids, n_graphs: int, mask=None):
        return scatter(self.op, x, graph_ids, n_graphs, mask=mask)


class AttentionPool(nn.Module):
    """Gated attention readout: softmax(gate(x)) per graph, then Σ α·proj(x)."""

    dim: int = 0  # 0 → keep input dim

    @nn.compact
    def __call__(self, x, graph_ids, n_graphs: int, mask=None):
        gate = nn.Dense(1)(x)[:, 0]
        alpha = scatter_softmax(gate, graph_ids, n_graphs, mask=mask)
        h = nn.Dense(self.dim)(x) if self.dim else x
        return scatter("add", h * alpha[:, None], graph_ids, n_graphs, mask=mask)


class Set2SetPool(nn.Module):
    """Set2Set readout (order-invariant LSTM attention, set2set parity).

    T rounds of: query ← LSTM(prev read); α = softmax(x·q); read = Σ αx;
    output is [q ‖ read] per graph (2×dim)."""

    steps: int = 3

    @nn.compact
    def __call__(self, x, graph_ids, n_graphs: int, mask=None):
        d = x.shape[-1]
        cell = nn.LSTMCell(features=d)
        carry = cell.initialize_carry(
            jax.random.PRNGKey(0), (n_graphs, d)
        )
        q_star = jnp.zeros((n_graphs, 2 * d), x.dtype)
        for _ in range(self.steps):
            carry, q = cell(carry, q_star)
            e = jnp.sum(x * gather(q, graph_ids), axis=-1)
            alpha = scatter_softmax(e, graph_ids, n_graphs, mask=mask)
            read = scatter(
                "add", x * alpha[:, None], graph_ids, n_graphs, mask=mask
            )
            q_star = jnp.concatenate([q, read], axis=-1)
        return q_star


POOLS = {
    "add": lambda: Pooling(op="add"),
    "mean": lambda: Pooling(op="mean"),
    "max": lambda: Pooling(op="max"),
    "attention": AttentionPool,
    "set2set": Set2SetPool,
}
