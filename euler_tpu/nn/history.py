"""Host-side history embedding table for scalable (1-hop) training.

The reference's ScalableGCN/ScalableSage trick (utils/encoders.py:294-410,
629-750): keep every node's last-known activation in a table, train with a
1-hop receptive field per step using stored activations for the frontier,
and refresh the stored rows with a moving average. PS variables become a
host numpy table (or, sharded, one slice per host); device steps stay O(1)
in depth.
"""

from __future__ import annotations

import numpy as np


class HistoryTable:
    def __init__(self, num_nodes: int, dim: int, momentum: float = 0.9):
        self.table = np.zeros((num_nodes + 1, dim), dtype=np.float32)
        self.momentum = momentum
        self.num_nodes = num_nodes

    def _rows(self, ids: np.ndarray) -> np.ndarray:
        return np.clip(ids.astype(np.int64), 0, self.num_nodes)

    def fetch(self, ids: np.ndarray) -> np.ndarray:
        return self.table[self._rows(ids)]

    def update(self, ids: np.ndarray, values: np.ndarray) -> None:
        rows = self._rows(ids)
        m = self.momentum
        self.table[rows] = m * self.table[rows] + (1 - m) * np.asarray(values)
