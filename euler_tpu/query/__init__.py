from euler_tpu.query.gql import Query, run_gql  # noqa: F401
