from euler_tpu.query.gql import Query, register_udf, run_gql, unregister_udf  # noqa: F401
