from euler_tpu.query.gql import Query, register_udf, run_gql, unregister_udf  # noqa: F401
from euler_tpu.query.plan import (  # noqa: F401
    execute_plan,
    fanout_plan,
    full_neighbor_plan,
    plan_from_steps,
    plan_mode,
    run_plan,
)
