"""GQL — gremlin-like graph query chains compiled to batch ops.

The reference compiles GQL strings through flex/bison → DAG → optimizer →
kernels (euler/parser/gremlin.l:15-56, gremlin.y, compiler.h:35-196). Every
tf_euler kernel actually emits a fixed template like
`v(nodes).sampleNB(et0,et1,n).as(nb)` (sample_fanout_op.cc:36-49), so the
TPU build compiles the same surface straight to the vectorized batch API —
the scatter/REMOTE/merge machinery already lives in the Graph facade.

Supported steps (token names follow gremlin.l):
  sources:  v(ids|param) | e(param) | sampleN(type, n) | sampleE(type, n)
  traverse: sampleNB(t..., n) | sampleLNB(t..., n) | outV(t...) | inV(t...)
  fetch:    values(f...) | label() | get()
  filter:   has_type(t) | limit(n) | order_by(id|weight[, desc])
  name:     as(alias)

`Query(gql).run(graph, inputs)` returns {alias: result}. Neighbor aliases
map to (ids, weights, types, mask); values aliases to feature arrays.
"""

from __future__ import annotations

import re

import numpy as np

from euler_tpu.graph.store import DEFAULT_ID

_TOKEN = re.compile(
    r"\s*(?:(?P<name>[A-Za-z_][A-Za-z0-9_]*)|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<str>'[^']*'|\"[^\"]*\")|(?P<punct>[().,\[\]]))"
)


def _tokenize(src: str):
    src = src.strip()
    pos = 0
    out = []
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            raise SyntaxError(f"bad GQL at …{src[pos:pos + 20]!r}")
        pos = m.end()
        if m.group("name") is not None:
            out.append(("name", m.group("name")))
        elif m.group("num") is not None:
            n = m.group("num")
            out.append(("num", float(n) if "." in n else int(n)))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1]))
        else:
            out.append(("punct", m.group("punct")))
    return out


def _parse(src: str) -> list[tuple[str, list]]:
    """'.'-chained calls → [(fn_name, args), ...]."""
    toks = _tokenize(src)
    i = 0
    calls = []

    def expect(kind, val=None):
        nonlocal i
        if i >= len(toks) or toks[i][0] != kind or (
            val is not None and toks[i][1] != val
        ):
            got = toks[i] if i < len(toks) else ("eof", "")
            raise SyntaxError(f"expected {val or kind}, got {got[1]!r}")
        i += 1
        return toks[i - 1][1]

    try:
        while i < len(toks):
            fn = expect("name")
            args = []
            expect("punct", "(")
            while toks[i] != ("punct", ")"):
                kind, val = toks[i]
                if kind in ("num", "str", "name"):
                    args.append(val)
                    i += 1
                elif (kind, val) == ("punct", "["):
                    i += 1
                    lst = []
                    while toks[i] != ("punct", "]"):
                        if toks[i][0] in ("num", "str"):
                            lst.append(toks[i][1])
                        elif toks[i] == ("punct", ","):
                            pass
                        else:
                            raise SyntaxError(
                                f"unexpected {toks[i][1]!r} inside [...] "
                                "(only literals allowed)"
                            )
                        i += 1
                    i += 1
                    args.append(lst)
                else:
                    raise SyntaxError(f"unexpected {val!r} in argument list")
                if i < len(toks) and toks[i] == ("punct", ","):
                    i += 1
            expect("punct", ")")
            calls.append((fn, args))
            if i < len(toks):
                expect("punct", ".")
    except IndexError:
        raise SyntaxError("unexpected end of GQL input") from None
    return calls


class Query:
    """Compiled GQL chain; compile once, run per batch (Compiler cache
    parity, compiler.h:112-126)."""

    def __init__(self, gql: str):
        self.gql = gql
        self.calls = _parse(gql)
        if not self.calls:
            raise SyntaxError("empty query")

    def run(self, graph, inputs: dict | None = None, rng=None) -> dict:
        inputs = inputs or {}
        rng = rng if rng is not None else np.random.default_rng()
        cur: np.ndarray | None = None  # current node frontier (u64)
        last: object = None  # last step's full result
        results: dict[str, object] = {}

        def resolve_ids(arg):
            if isinstance(arg, str):
                return np.asarray(inputs[arg], dtype=np.uint64)
            if isinstance(arg, list):
                return np.asarray(arg, dtype=np.uint64)
            return np.asarray([arg], dtype=np.uint64)

        for fn, args in self.calls:
            if fn == "v":
                cur = resolve_ids(args[0])
                last = cur
            elif fn == "e":
                edges = np.asarray(inputs[args[0]], dtype=np.uint64)
                cur = edges[:, 1]  # frontier = dst
                last = edges
            elif fn == "sampleN":
                t, n = int(args[0]), int(args[1])
                cur = graph.sample_node(n, t, rng=rng)
                last = cur
            elif fn == "sampleE":
                t, n = int(args[0]), int(args[1])
                last = graph.sample_edge(n, t, rng=rng)
                cur = last[:, 1]
            elif fn in ("sampleNB", "outV", "inV", "sampleLNB"):
                *types, n = args if fn in ("sampleNB", "sampleLNB") else (
                    list(args) + [0]
                )
                et = [int(t) for t in types] if types else None
                if fn == "sampleNB":
                    nbr, w, tt, mask, _ = graph.sample_neighbor(
                        cur, et, int(n), rng=rng
                    )
                    last = (nbr, w, tt, mask)
                    cur = nbr.reshape(-1)
                elif fn == "sampleLNB":
                    layer, adj, lmask = graph.sample_neighbor_layerwise(
                        cur, et, int(n), rng=rng
                    )
                    last = (layer, adj, lmask)
                    cur = layer
                else:
                    nbr, w, tt, mask, _ = graph.get_full_neighbor(
                        cur, et, in_edges=(fn == "inV")
                    )
                    last = (nbr, w, tt, mask)
                    cur = nbr.reshape(-1)
            elif fn == "values":
                last = graph.get_dense_feature(cur, [str(a) for a in args])
            elif fn == "label":
                last = graph.node_type(cur)
            elif fn == "get":
                last = cur
            elif fn == "has_type":
                keep = graph.node_type(cur) == int(args[0])
                cur = np.where(keep, cur, DEFAULT_ID)
                last = cur
            elif fn == "limit":
                n = int(args[0])
                if isinstance(last, tuple):
                    # row-wise truncation of the previous step's result
                    last = tuple(x[:n] for x in last)
                    cur = np.asarray(last[0]).reshape(-1)
                else:
                    cur = cur[:n]
                    if isinstance(last, np.ndarray):
                        last = last[:n]
            elif fn == "order_by":
                if not (isinstance(last, tuple) and len(last) == 4):
                    raise ValueError("order_by follows a neighbor step")
                nbr, w, tt, mask = last
                key = w if args[0] == "weight" else nbr
                desc = len(args) > 1 and str(args[1]).lower() == "desc"
                order = np.argsort(-key if desc else key, axis=1, kind="stable")
                take = np.take_along_axis
                last = (
                    take(nbr, order, 1),
                    take(w, order, 1),
                    take(tt, order, 1),
                    take(mask, order, 1),
                )
                cur = last[0].reshape(-1)
            elif fn == "as":
                results[str(args[0])] = last
            else:
                raise ValueError(f"unknown GQL step {fn!r}")
        results.setdefault("_", last)
        return results


def run_gql(graph, gql: str, inputs=None, rng=None) -> dict:
    return Query(gql).run(graph, inputs, rng=rng)
