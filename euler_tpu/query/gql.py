"""GQL — gremlin-like graph query chains compiled to batch ops.

The reference compiles GQL strings through flex/bison → DAG → optimizer →
kernels (euler/parser/gremlin.l:15-56, gremlin.y, compiler.h:35-196). Every
tf_euler kernel actually emits a fixed template like
`v(nodes).sampleNB(et0,et1,n).as(nb)` (sample_fanout_op.cc:36-49), so the
TPU build compiles the same surface straight to the vectorized batch API —
the scatter/REMOTE/merge machinery already lives in the Graph facade, and
`has*` conditions push down into the index subsystem
(euler_tpu/graph/index.py) exactly where the reference's compiler pushes
index_info (compiler.h:37-41).

Supported steps (token names follow gremlin.l:15-56):
  sources:  v(ids|param) | e(param) | sampleN(type, n) | sampleE(type, n)
            | sampleNWithTypes([t...], n)
  traverse: sampleNB(t..., n) | sampleLNB(t..., n) | outV(t...) | inV(t...)
            | outE(t...)
  fetch:    values(f | udf_mean(f) | udf_min(f) | udf_max(f), ...) | label()
            | get()
  filter:   has(f, v) | has(f, gt(v)|ge|lt|le|eq|ne|in_([..])|not_in([..]))
            | hasKey(f) | hasLabel(t) | or_()      [conditions attach to the
            preceding source/traverse step; or_() starts a new DNF clause]
            | has_type(t) | limit(n) | order_by(id|weight[, desc])
  name:     as(alias)

`Query(gql).run(graph, inputs)` returns {alias: result}. Neighbor aliases
map to (ids, weights, types, mask); values aliases to feature arrays.
"""

from __future__ import annotations

import functools
import os
import re
import threading

import numpy as np

from euler_tpu.graph.store import DEFAULT_ID

_TOKEN = re.compile(
    r"\s*(?:(?P<name>[A-Za-z_][A-Za-z0-9_]*)|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<str>'[^']*'|\"[^\"]*\")|(?P<punct>[().,\[\]]))"
)

_COND_STEPS = ("has", "hasKey", "hasLabel", "or_")
_SOURCE_OR_TRAVERSE = (
    "v", "e", "sampleN", "sampleE", "sampleNWithTypes",
    "sampleNB", "sampleLNB", "outV", "inV", "outE",
)
_CMP_OPS = ("gt", "ge", "lt", "le", "eq", "ne", "in_", "not_in")
# feature-aggregation UDFs callable as values(udf_*(feat)); the builtins
# mirror the kernels the reference registers (euler/core/framework/udf.h:
# 30-60, mean/min/max) + sum. Extend with register_udf().
_UDFS = {
    "udf_mean": lambda b: np.mean(b, axis=1, keepdims=True),
    "udf_min": lambda b: np.min(b, axis=1, keepdims=True),
    "udf_max": lambda b: np.max(b, axis=1, keepdims=True),
    "udf_sum": lambda b: np.sum(b, axis=1, keepdims=True),
}


def register_udf(name: str, fn) -> None:
    """Register a user feature-aggregation UDF (udf.h:30-60 parity).

    `fn(block)` receives the fetched feature block `f32[n, dim]` and must
    return `[n]` or `[n, k]`. The aggregation runs client-side over the
    batched fetch, so one registration covers local, partitioned, and
    remote graphs alike (the reference runs UDFs on the serving shard
    because its fetches are per-record; here the fetch is already one
    vectorized batch, so post-aggregation is a free tail op).
    """
    if not name.startswith("udf_"):
        raise ValueError(f"UDF names must start with 'udf_': {name!r}")
    if not callable(fn):
        raise TypeError("fn must be callable")
    _UDFS[name] = fn


def unregister_udf(name: str) -> None:
    """Remove a user-registered UDF; builtins cannot be removed."""
    if name in ("udf_mean", "udf_min", "udf_max", "udf_sum"):
        raise ValueError(f"cannot unregister builtin UDF {name!r}")
    _UDFS.pop(name, None)


def apply_udf(name: str, block: np.ndarray) -> np.ndarray:
    """Run registered UDF `name` on a fetched [n, dim] block → [n, k].

    Shared by the client-side values() tail and the serving shard's
    dense_feature_udf op (server-side aggregation, udf.h API_GET_P
    semantics) so both sides validate shapes identically."""
    if name not in _UDFS:
        raise ValueError(f"unknown UDF {name!r}")
    n_rows = block.shape[0]
    out = np.asarray(_UDFS[name](block), dtype=np.float32)
    if out.ndim == 1:
        out = out.reshape(-1, 1)
    if out.ndim != 2 or out.shape[0] != n_rows:
        raise ValueError(
            f"UDF {name!r} returned shape {out.shape}; expected"
            f" [{n_rows}] or [{n_rows}, k] (one row per frontier node —"
            " aggregate over axis=1)"
        )
    return out


def dense_feature_udf(graph, ids, names, udfs):
    """Aggregated dense-feature fetch: per (name, udf) pair, fetch the
    feature block for `ids` and return only the aggregates:
    ([n, sum(k_i)] f32, [k_i...] int64 per-pair column widths).

    This is what a serving shard executes for remote `values(udf_*)`
    (the reference runs UDFs on the shard that owns the data and ships
    only the aggregate — euler/core/framework/udf.h, API_GET_P kernels);
    the wire then carries k columns instead of the feature dim."""
    ids = np.asarray(ids, np.uint64)
    names = list(names)
    widths = [graph.meta.feature_spec(nm, node=True).dim for nm in names]
    flat = graph.get_dense_feature(ids, names)
    offs = _offsets(widths)
    cols = [
        apply_udf(udf, flat[:, offs[k] : offs[k + 1]])
        for k, udf in enumerate(udfs)
    ]
    out = (
        np.concatenate(cols, axis=1)
        if cols
        else np.zeros((len(ids), 0), np.float32)
    )
    return out, np.asarray([c.shape[1] for c in cols], np.int64)


def _tokenize(src: str):
    src = src.strip()
    pos = 0
    out = []
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            raise SyntaxError(f"bad GQL at …{src[pos:pos + 20]!r}")
        pos = m.end()
        if m.group("name") is not None:
            out.append(("name", m.group("name")))
        elif m.group("num") is not None:
            n = m.group("num")
            out.append(("num", float(n) if "." in n else int(n)))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1]))
        else:
            out.append(("punct", m.group("punct")))
    return out


def _parse(src: str) -> list[tuple[str, list]]:
    """'.'-chained calls → [(fn_name, args), ...]. Args may be literals,
    [lists], or one-level nested calls like gt(3) / udf_mean(f)."""
    toks = _tokenize(src)
    i = 0
    calls = []

    def expect(kind, val=None):
        nonlocal i
        if i >= len(toks) or toks[i][0] != kind or (
            val is not None and toks[i][1] != val
        ):
            got = toks[i] if i < len(toks) else ("eof", "")
            raise SyntaxError(f"expected {val or kind}, got {got[1]!r}")
        i += 1
        return toks[i - 1][1]

    def parse_list():
        nonlocal i
        i += 1  # consume '['
        lst = []
        while toks[i] != ("punct", "]"):
            if toks[i][0] in ("num", "str"):
                lst.append(toks[i][1])
            elif toks[i] == ("punct", ","):
                pass
            else:
                raise SyntaxError(
                    f"unexpected {toks[i][1]!r} inside [...] (literals only)"
                )
            i += 1
        i += 1
        return lst

    def parse_args():
        nonlocal i
        args = []
        expect("punct", "(")
        while toks[i] != ("punct", ")"):
            kind, val = toks[i]
            if kind == "name" and i + 1 < len(toks) and toks[i + 1] == (
                "punct", "("
            ):
                i += 1
                args.append(("()", val, parse_args()))
            elif kind in ("num", "str", "name"):
                args.append(val)
                i += 1
            elif (kind, val) == ("punct", "["):
                args.append(parse_list())
            else:
                raise SyntaxError(f"unexpected {val!r} in argument list")
            if i < len(toks) and toks[i] == ("punct", ","):
                i += 1
        expect("punct", ")")
        return args

    try:
        while i < len(toks):
            fn = expect("name")
            calls.append((fn, parse_args()))
            if i < len(toks):
                expect("punct", ".")
    except IndexError:
        raise SyntaxError("unexpected end of GQL input") from None
    return calls


def _cond_atom(fn: str, args: list):
    """A has/hasKey/hasLabel call → one DNF atom (field, op, value)."""
    if fn == "hasKey":
        return (str(args[0]), "haskey", None)
    if fn == "hasLabel":
        return ("type", "eq", args[0])
    field = str(args[0])
    if len(args) == 1:
        return (field, "haskey", None)
    v = args[1]
    if isinstance(v, tuple) and v[0] == "()":
        op = v[1]
        if op not in _CMP_OPS:
            raise SyntaxError(f"unknown comparison {op!r}")
        inner = v[2][0] if len(v[2]) == 1 else list(v[2])
        if op in ("in_", "not_in") and not isinstance(inner, list):
            inner = [inner]
        return (field, "in" if op == "in_" else op, inner)
    return (field, "eq", v)


def _compile(calls):
    """Fold has*/or_ steps into DNF conditions on the preceding step."""
    steps = []
    for fn, args in calls:
        if fn in _COND_STEPS:
            if not steps or steps[-1][0] not in _SOURCE_OR_TRAVERSE:
                raise SyntaxError(f"{fn} must follow a source/traverse step")
            conds = steps[-1][2]
            if fn == "or_":
                if conds and conds[-1]:
                    conds.append([])
            else:
                if not conds:
                    conds.append([])
                conds[-1].append(_cond_atom(fn, args))
        else:
            steps.append((fn, args, []))
    return steps


_RNG_TLS = threading.local()


def _default_rng():
    """Per-thread fallback Generator — constructing a fresh default_rng
    costs ~40us (OS entropy), which would dominate hot-loop dispatch, and
    numpy Generators are not thread-safe so the cache is thread-local
    (queries run on prefetch producer threads, estimator/prefetch.py)."""
    rng = getattr(_RNG_TLS, "rng", None)
    if rng is None:
        rng = _RNG_TLS.rng = np.random.default_rng()
    return rng


def _offsets(widths):
    """[w0, w1, ...] → [0, w0, w0+w1, ...] without numpy (np.r_ costs ~19us)."""
    offs = [0]
    for w in widths:
        offs.append(offs[-1] + int(w))
    return offs


@functools.lru_cache(maxsize=512)
def _compile_cached(gql: str):
    """Query-string → (steps, plans), cached across Query instances
    (reference caches GQL→DAG per query string, compiler.h:112-126).

    `plans[i]` holds the static half of step i's work: for `values` steps
    the resolved feature-name tuple and (position, udf_name) pairs, so the
    hot loop does zero per-call arg introspection."""
    steps = _compile(_parse(gql))
    if not steps:
        raise SyntaxError("empty query")
    plans = []
    for fn, args, _conds in steps:
        if fn == "values":
            names = tuple(
                str(a[2][0]) if isinstance(a, tuple) else str(a)
                for a in args
            )
            udf_pairs = tuple(
                (k, a[1]) for k, a in enumerate(args)
                if isinstance(a, tuple) and a[0] == "()"
            )
            plans.append((names, udf_pairs))
        else:
            plans.append(None)
    return tuple(steps), tuple(plans)


class Query:
    """Compiled GQL chain; compile once per unique string, run per batch
    (Compiler cache parity, compiler.h:112-126)."""

    def __init__(self, gql: str):
        self.gql = gql
        steps, plans = _compile_cached(gql)
        self.steps = list(steps)
        self._plans = plans
        # serializable per-shard sub-plan (SPLIT → exec_plan → MERGE),
        # or None when a step is not shard-fusable — then every graph
        # takes the per-op loop below
        from euler_tpu.query.plan import plan_from_steps

        self._remote_plan = plan_from_steps(self.steps, self._plans)

    def run(self, graph, inputs: dict | None = None, rng=None) -> dict:
        inputs = inputs or {}
        rng = rng if rng is not None else _default_rng()
        if self._remote_plan is not None and (
            os.environ.get("EULER_TPU_FUSED_PLAN", "1") != "off"
        ):
            from euler_tpu.query.plan import is_remote_graph, run_plan

            if is_remote_graph(graph):
                # remote cluster: one fused exec_plan RPC per owner shard
                # (or the seed-compatible per-op mode when
                # EULER_TPU_FUSED_PLAN=0) instead of one round per step
                plan, root_arg = self._remote_plan
                if isinstance(root_arg, str):
                    roots = np.asarray(inputs[root_arg], dtype=np.uint64)
                elif isinstance(root_arg, list):
                    roots = np.asarray(root_arg, dtype=np.uint64)
                else:
                    roots = np.asarray([root_arg], dtype=np.uint64)
                seed = int(rng.integers(0, 2**63 - 1))
                return run_plan(graph, plan, roots, seed)
        cur: np.ndarray | None = None  # current node frontier (u64)
        cur_edges: np.ndarray | None = None  # [n,3] edge frontier after e/outE
        last: object = None  # last step's full result
        results: dict[str, object] = {}

        def resolve_ids(arg):
            if isinstance(arg, str):
                return np.asarray(inputs[arg], dtype=np.uint64)
            if isinstance(arg, list):
                return np.asarray(arg, dtype=np.uint64)
            return np.asarray([arg], dtype=np.uint64)

        def resolve_dnf(conds):
            """Resolve type names in hasLabel atoms against graph meta."""
            out = []
            for clause in conds:
                c = []
                for field, op, value in clause:
                    if field == "type" and isinstance(value, str):
                        value = graph.meta.node_type_id(value)
                    c.append((field, op, value))
                out.append(c)
            return out

        def filter_frontier(ids, conds):
            keep = graph.condition_mask(ids, resolve_dnf(conds))
            return np.where(keep, ids, DEFAULT_ID)

        for (fn, args, conds), plan in zip(self.steps, self._plans):
            if fn == "v":
                cur_edges = None
                cur = resolve_ids(args[0])
                if conds:
                    cur = filter_frontier(cur, conds)
                last = cur
            elif fn == "e":
                edges = np.asarray(inputs[args[0]], dtype=np.uint64)
                if conds:
                    keep = graph.condition_mask(
                        edges, resolve_dnf(conds), node=False
                    )
                    edges = edges[keep]
                cur = edges[:, 1]  # frontier = dst
                cur_edges = edges
                last = edges
            elif fn == "sampleN":
                cur_edges = None
                t, n = int(args[0]), int(args[1])
                if conds:
                    cur = graph.sample_node_with_condition(
                        n, resolve_dnf(conds), node_type=t, rng=rng
                    )
                else:
                    cur = graph.sample_node(n, t, rng=rng)
                last = cur
            elif fn == "sampleNWithTypes":
                cur_edges = None
                types, n = args[0], int(args[1])
                types = types if isinstance(types, list) else [types]
                per = [
                    graph.sample_node_with_condition(
                        n, resolve_dnf(conds), node_type=int(t), rng=rng
                    )
                    if conds
                    else graph.sample_node(n, int(t), rng=rng)
                    for t in types
                ]
                last = np.stack(per)  # [T, n]
                cur = last.reshape(-1)
            elif fn == "sampleE":
                t, n = int(args[0]), int(args[1])
                if conds:  # exact-count index-conditioned edge sampling
                    last = graph.sample_edge_with_condition(
                        n, resolve_dnf(conds), edge_type=t, rng=rng
                    )
                else:
                    last = graph.sample_edge(n, t, rng=rng)
                cur = last[:, 1]
                cur_edges = np.asarray(last, dtype=np.uint64)
            elif fn in ("sampleNB", "outV", "inV", "sampleLNB"):
                cur_edges = None
                *types, n = args if fn in ("sampleNB", "sampleLNB") else (
                    list(args) + [0]
                )
                et = [int(t) for t in types] if types else None
                if fn == "sampleNB":
                    nbr, w, tt, mask, _ = graph.sample_neighbor(
                        cur, et, int(n), rng=rng
                    )
                elif fn == "sampleLNB":
                    layer, adj, lmask = graph.sample_neighbor_layerwise(
                        cur, et, int(n), rng=rng
                    )
                    if conds:  # filter the shared layer candidate set
                        keep = graph.condition_mask(layer, resolve_dnf(conds))
                        layer = np.where(keep, layer, DEFAULT_ID)
                        adj = np.where(keep[None, :], adj, 0.0)
                        lmask = lmask & keep
                    last = (layer, adj, lmask)
                    cur = layer
                    continue
                else:
                    nbr, w, tt, mask, _ = graph.get_full_neighbor(
                        cur, et, in_edges=(fn == "inV")
                    )
                if conds:  # nb-filter semantics (API_GET_NB_FILTER)
                    keep = graph.condition_mask(
                        nbr.reshape(-1), resolve_dnf(conds)
                    ).reshape(nbr.shape)
                    keep &= mask
                    nbr = np.where(keep, nbr, DEFAULT_ID)
                    w = np.where(keep, w, 0.0).astype(np.float32)
                    tt = np.where(keep, tt, -1)
                    mask = keep
                last = (nbr, w, tt, mask)
                cur = nbr.reshape(-1)
            elif fn == "outE":
                et = [int(t) for t in args] if args else None
                nbr, w, tt, mask, eidx = graph.get_full_neighbor(cur, et)
                if conds:  # filter edges whose destination fails the DNF
                    keep = graph.condition_mask(
                        nbr.reshape(-1), resolve_dnf(conds)
                    ).reshape(nbr.shape)
                    mask = mask & keep
                    nbr = np.where(mask, nbr, DEFAULT_ID)
                    w = np.where(mask, w, 0.0).astype(np.float32)
                src = np.broadcast_to(
                    np.asarray(cur, dtype=np.uint64)[:, None], nbr.shape
                )
                triples = np.stack(
                    [src, nbr, np.maximum(tt, 0).astype(np.uint64)], axis=-1
                )  # [n, D, 3]
                cur_edges = triples.reshape(-1, 3)
                last = (triples, w, mask)
            elif fn == "values":
                # one batched fetch for every referenced feature, then
                # splice/aggregate per-arg columns in order; after an edge
                # step (e/sampleE/outE) this reads EDGE features, matching
                # the reference's get_feature kernel accepting edge_ids
                names, udf_pairs = plan
                if names and not udf_pairs:
                    # fast path: the per-arg column slices concatenated in
                    # order ARE the batched fetch — return it untouched
                    last = (
                        graph.get_edge_dense_feature(cur_edges, list(names))
                        if cur_edges is not None
                        else graph.get_dense_feature(cur, list(names))
                    )
                elif names:
                    on_edges = cur_edges is not None
                    udf_idx = [k for k, _ in udf_pairs]
                    pushdown = getattr(graph, "get_dense_feature_udf", None)
                    udf_cols = None
                    if udf_idx and not on_edges and pushdown is not None:
                        # server-side aggregation (udf.h semantics): the
                        # owning shard runs the UDF and ships only the
                        # aggregate columns. A server that doesn't know
                        # the (client-registered) UDF raises; fall back
                        # to fetching the block and aggregating here.
                        try:
                            agg, agg_w = pushdown(
                                cur,
                                [names[k] for k in udf_idx],
                                [args[k][1] for k in udf_idx],
                            )
                        except (RuntimeError, ValueError) as e:
                            # only capability gaps fall back: a server
                            # predating the op ("unknown op ...") or one
                            # without this client-registered UDF
                            # ("unknown UDF ..."); genuine execution
                            # failures must surface, not be silently
                            # recomputed client-side
                            s = str(e)
                            if "unknown op" not in s and (
                                "unknown UDF" not in s
                            ):
                                raise
                            agg = None
                        if agg is not None:
                            # split the concatenated aggregate back into
                            # per-arg columns by the reported widths (a
                            # UDF may return k>1 columns)
                            ao = _offsets(agg_w)
                            udf_cols = [
                                agg[:, ao[i] : ao[i + 1]]
                                for i in range(len(udf_idx))
                            ]
                    fetch_idx = [
                        k for k in range(len(args))
                        if udf_cols is None or k not in udf_idx
                    ]
                    flat = None
                    offs = None
                    if fetch_idx:
                        fetch_names = [names[k] for k in fetch_idx]
                        widths = [
                            graph.meta.feature_spec(
                                nm, node=not on_edges
                            ).dim
                            for nm in fetch_names
                        ]
                        flat = (
                            graph.get_edge_dense_feature(
                                cur_edges, fetch_names
                            )
                            if on_edges
                            else graph.get_dense_feature(cur, fetch_names)
                        )
                        offs = _offsets(widths)
                    cols = []
                    fpos = 0
                    upos = 0
                    for k, a in enumerate(args):
                        if udf_cols is not None and k in udf_idx:
                            cols.append(udf_cols[upos])
                            upos += 1
                            continue
                        block = flat[:, offs[fpos] : offs[fpos + 1]]
                        fpos += 1
                        if isinstance(a, tuple) and a[0] == "()":
                            block = apply_udf(a[1], block)
                        cols.append(block)
                    last = np.concatenate(cols, axis=1)
                else:
                    last = None
            elif fn == "label":
                last = graph.node_type(cur)
            elif fn == "get":
                cur_edges = None  # result is the node frontier
                last = cur
            elif fn == "has_type":
                cur_edges = None  # frontier moves back to nodes
                keep = graph.node_type(cur) == int(args[0])
                cur = np.where(keep, cur, DEFAULT_ID)
                last = cur
            elif fn == "limit":
                n = int(args[0])
                if isinstance(last, tuple) and len(last) == 4:
                    # row-wise truncation of a neighbor step's result
                    last = tuple(x[:n] for x in last)
                    cur = np.asarray(last[0]).reshape(-1)
                elif isinstance(last, tuple) and len(last) == 3:
                    # outE triples / layerwise: truncate source rows only;
                    # the frontier (and layer candidate set) is unchanged
                    triples, w, mask = last
                    if triples.ndim == 3:  # outE
                        last = (triples[:n], w[:n], mask[:n])
                        cur_edges = triples[:n].reshape(-1, 3)
                    else:
                        raise ValueError("limit after sampleLNB is undefined")
                elif isinstance(last, np.ndarray) and last.ndim == 2 and (
                    cur_edges is None
                ):
                    # sampleNWithTypes result [T, n]: limit per type so the
                    # flattened frontier and the stored result stay aligned
                    last = last[:, :n]
                    cur = last.reshape(-1)
                else:
                    cur = cur[:n]
                    if isinstance(last, np.ndarray):
                        last = last[:n]
                    if cur_edges is not None:  # keep edge frontier in step
                        cur_edges = cur_edges[:n]
            elif fn == "order_by":
                cur_edges = None  # neighbor-step result: node frontier
                if not (isinstance(last, tuple) and len(last) == 4):
                    raise ValueError("order_by follows a neighbor step")
                nbr, w, tt, mask = last
                key = w if args[0] == "weight" else nbr
                desc = len(args) > 1 and str(args[1]).lower() == "desc"
                order = np.argsort(-key if desc else key, axis=1, kind="stable")
                take = np.take_along_axis
                last = (
                    take(nbr, order, 1),
                    take(w, order, 1),
                    take(tt, order, 1),
                    take(mask, order, 1),
                )
                cur = last[0].reshape(-1)
            elif fn == "as":
                results[str(args[0])] = last
            else:
                raise ValueError(f"unknown GQL step {fn!r}")
        results.setdefault("_", last)
        return results


def run_gql(graph, gql: str, inputs=None, rng=None) -> dict:
    return Query(gql).run(graph, inputs, rng=rng)
