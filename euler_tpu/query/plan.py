"""Distributed query planner: fused per-shard remote sub-plans.

The reference compiles a GQL traversal into SPLIT → per-shard REMOTE
(fused sub-plan) → MERGE, so an L-step query on a P-shard cluster costs
~P client RPCs (euler/parser/optimizer.h:49-86, remote_op.cc:31-120).
This module is that optimizer for the TPU build: a compiled GQL chain
(or a dataflow's fanout request) becomes a serializable PLAN — a list of
op descriptors with arg bindings — and the client

  1. SPLITs the root frontier by owner shard (``id % P``),
  2. issues ONE pipelined ``exec_plan`` RPC per non-empty shard (the
     server runs the whole sub-plan next to the data, scattering
     intermediate hops worker-to-worker through its cluster facade), and
  3. MERGEs the per-shard results back into root order, padded exactly
     like the per-op scatter-gather path.

Determinism contract: every random draw is keyed by an explicit integer
seed derived from ``(base_seed, subset, step, shard)``, never by shared
Generator stream position. A local store receives
``default_rng(seed)``; a remote shard receives the raw seed (the server
builds the identical ``default_rng(seed)``). Because the per-op
fallback executes the SAME per-subset plan with the SAME derived seeds,
fused and per-op runs are bit-identical for a fixed seed — the A/B
parity the planner tests pin down.

``EULER_TPU_FUSED_PLAN`` selects the execution mode:
  "1" (default) — fused: one exec_plan RPC per shard;
  "0"           — per-op: the client drives each step itself (the
                  legacy ~L×P-round-trip path, kept for A/B parity);
  "off"         — bypass the planner entirely (pre-planner routing);
a server predating the ``exec_plan`` verb degrades to per-op for that
subset automatically (same seeds → same results).
"""

from __future__ import annotations

import json
import os

import numpy as np

from euler_tpu.graph.store import DEFAULT_ID

# GQL steps the planner can ship to a shard. Anything outside this set
# (global sources like sampleN/sampleE, batch-global steps like limit /
# sampleLNB, edge frontiers) keeps the legacy per-op execution.
_TERMINAL_AFTER_DYNAMIC = ("as", "order_by")

# The planner's own wire surface (the fused-dispatch verb of PR 1);
# graftlint's wire-protocol checker unions this with RemoteShard's
# WIRE_VERBS and diffs against the graph server's HANDLED_VERBS.
WIRE_VERBS = frozenset({"exec_plan"})


def plan_mode() -> str:
    """EULER_TPU_FUSED_PLAN: "1" → fused (default), "0" → per-op A/B
    fallback, "off" → skip the planner entirely (legacy routing)."""
    v = os.environ.get("EULER_TPU_FUSED_PLAN", "1")
    if v == "0":
        return "per-op"
    if v == "off":
        return "off"
    return "fused"


def _fused_enabled() -> bool:
    return plan_mode() == "fused"


def step_seed(base: int, step: int, part: int) -> int:
    """Deterministic per-(step, shard) sampling seed. Both execution
    modes (fused server-side, per-op client-side) derive draws from this
    — stream position never leaks between shards or steps."""
    ss = np.random.SeedSequence([int(base) & (2**63 - 1), int(step), int(part)])
    # 63-bit: seeds ride the wire as signed i64
    return int(ss.generate_state(1, np.uint64)[0]) & (2**63 - 1)


def subset_seed(base: int, part: int) -> int:
    """Base seed of one owner-subset's sub-plan execution."""
    ss = np.random.SeedSequence([int(base) & (2**63 - 1), int(part)])
    return int(ss.generate_state(1, np.uint64)[0]) & (2**63 - 1)


class _FixedSeed:
    """rng stand-in whose only draw IS the seed: RemoteShard methods call
    ``rng.integers(...)`` to pick the seed they put on the wire, so
    handing them this object makes the server build ``default_rng(seed)``
    — exactly what a local store receives. That equivalence is what makes
    fused (server executes) and per-op (client executes) bit-identical."""

    __slots__ = ("seed",)

    def __init__(self, seed: int):
        self.seed = int(seed)

    def integers(self, *args, **kwargs):
        return self.seed


def _rng_for(shard, seed: int):
    if hasattr(shard, "call"):  # remote: ship the seed itself
        return _FixedSeed(seed)
    return np.random.default_rng(seed)


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------


def _conds_json(conds) -> list | None:
    """DNF conditions → plain JSON-able lists (numpy scalars unwrapped)."""
    if not conds:
        return None
    clean = lambda v: v.item() if hasattr(v, "item") else v
    return [
        [[f, o, [clean(x) for x in v] if isinstance(v, list) else clean(v)]
         for f, o, v in clause]
        for clause in conds
    ]


def plan_from_steps(steps, plans):
    """Compiled GQL steps → (plan, root_arg) or None when the chain is
    not shard-fusable. ``root_arg`` is the v() argument (param name or
    literal list); the ids themselves ride the exec_plan request as an
    array, never inside the plan."""
    if not steps or steps[0][0] != "v":
        return None
    plan = [{"op": "v", "conds": _conds_json(steps[0][2])}]
    root_arg = steps[0][1][0]
    dynamic = False  # a cap-less full_nb makes widths subset-dependent:
    # only row-wise tuple ops may follow (the merged tuple is re-padded)
    last_is_nb = False  # order_by is defined on a neighbor-step result
    for i, ((fn, args, conds), pre) in enumerate(zip(steps, plans)):
        if i == 0:
            continue
        if dynamic and fn not in _TERMINAL_AFTER_DYNAMIC:
            return None
        if fn == "sampleNB":
            *types, n = args
            plan.append({
                "op": "sample_nb",
                "et": [int(t) for t in types] if types else None,
                "n": int(n),
                "conds": _conds_json(conds),
            })
            last_is_nb = True
        elif fn in ("outV", "inV"):
            plan.append({
                "op": "full_nb",
                "et": [int(t) for t in args] if args else None,
                "in_edges": fn == "inV",
                "cap": None,
                "conds": _conds_json(conds),
            })
            dynamic = True
            last_is_nb = True
        elif fn == "values":
            names, udf_pairs = pre
            if not names:
                return None
            plan.append({
                "op": "values",
                "names": list(names),
                "udfs": [[int(k), u] for k, u in udf_pairs],
            })
            last_is_nb = False
        elif fn == "label":
            plan.append({"op": "label"})
            last_is_nb = False
        elif fn == "get":
            plan.append({"op": "get"})
            last_is_nb = False
        elif fn == "has_type":
            plan.append({"op": "has_type", "t": int(args[0])})
            last_is_nb = False
        elif fn == "order_by":
            if not last_is_nb:
                return None  # legacy raises "follows a neighbor step"
            plan.append({
                "op": "order_by",
                "key": str(args[0]),
                "desc": len(args) > 1 and str(args[1]).lower() == "desc",
            })
        elif fn == "as":
            plan.append({"op": "as", "name": str(args[0])})
        else:
            # e/sampleE/sampleN*/sampleLNB/outE/limit: batch-global or
            # edge-frontier semantics — per-op execution stays correct
            return None
    return plan, root_arg


def fanout_plan(edge_types, counts, label: str | None = None) -> list:
    """The dataflow fanout as a plan: L sampleNB hops + the global
    feature-cache rows of every hop (+ optional root labels)."""
    plan = [{"op": "v", "conds": None}]
    if label:
        plan.append({"op": "values", "names": [label], "udfs": [],
                     "as": "__labels"})
    et = None if edge_types is None else [int(t) for t in edge_types]
    for c in counts:
        plan.append({"op": "sample_nb", "et": et, "n": int(c), "conds": None})
    plan.append({"op": "rows"})
    return plan


def full_neighbor_plan(
    edge_types,
    num_hops: int,
    max_degree: int,
    feature_names=None,
    label: str | None = None,
    rows: bool = False,
    degrees: bool = False,
    root_features: bool = True,
) -> list:
    """FullNeighborDataFlow's whole query as one plan: per hop a capped
    full-neighbor expansion (+ features / true degrees), fetched next to
    the data instead of one RPC round per hop per kind.

    root_features=False drops the hop-0 feature tap (__f0): when the
    client's read cache already holds every root's rows, shipping them
    again is pure waste — the caller fills hop 0 from the cache. Results
    are bit-identical either way (the cache stores what the server
    serves), so both fused and per-op lanes of the SAME plan agree."""
    et = None if edge_types is None else [int(t) for t in edge_types]
    plan = [{"op": "v", "conds": None}]

    def tap(h, feats: bool = True):
        if feature_names and feats:
            plan.append({"op": "values", "names": list(feature_names),
                         "udfs": [], "as": f"__f{h}"})
        if degrees:
            plan.append({"op": "degree", "et": et, "as": f"__deg{h}"})

    if label:
        plan.append({"op": "values", "names": [label], "udfs": [],
                     "as": "__labels"})
    tap(0, feats=root_features)
    for h in range(num_hops):
        plan.append({"op": "full_nb", "et": et, "in_edges": False,
                     "cap": int(max_degree), "conds": None,
                     "as": f"__nb{h + 1}"})
        tap(h + 1)
    if rows:
        plan.append({"op": "rows"})
    return plan


# ---------------------------------------------------------------------------
# execution (runs on the server for fused mode, on the client for per-op)
# ---------------------------------------------------------------------------


def _resolve_dnf(graph, conds):
    out = []
    for clause in conds:
        c = []
        for field, op, value in clause:
            if field == "type" and isinstance(value, str):
                value = graph.meta.node_type_id(value)
            c.append((field, op, value))
        out.append(c)
    return out


def _offsets(widths):
    offs = [0]
    for w in widths:
        offs.append(offs[-1] + int(w))
    return offs


def _fetch_values(graph, cur, names, udf_pairs):
    """values() against a node frontier: one batched fetch, UDF pushdown
    to the owning shard when available (same fallback contract as the
    legacy executor in query/gql.py)."""
    from euler_tpu.query.gql import apply_udf

    if not udf_pairs:
        return graph.get_dense_feature(cur, list(names))
    udf_idx = [k for k, _ in udf_pairs]
    udf_names = {k: u for k, u in udf_pairs}
    pushdown = getattr(graph, "get_dense_feature_udf", None)
    udf_cols = None
    if pushdown is not None:
        try:
            agg, agg_w = pushdown(
                cur,
                [names[k] for k in udf_idx],
                [udf_names[k] for k in udf_idx],
            )
        except (RuntimeError, ValueError) as e:
            s = str(e)
            if "unknown op" not in s and "unknown UDF" not in s:
                raise
            agg = None
        if agg is not None:
            ao = _offsets(agg_w)
            udf_cols = [agg[:, ao[i]: ao[i + 1]] for i in range(len(udf_idx))]
    fetch_idx = [
        k for k in range(len(names))
        if udf_cols is None or k not in udf_idx
    ]
    flat = None
    offs = None
    if fetch_idx:
        fetch_names = [names[k] for k in fetch_idx]
        widths = [
            graph.meta.feature_spec(nm, node=True).dim for nm in fetch_names
        ]
        flat = graph.get_dense_feature(cur, fetch_names)
        offs = _offsets(widths)
    cols = []
    fpos = 0
    upos = 0
    for k in range(len(names)):
        if udf_cols is not None and k in udf_idx:
            cols.append(udf_cols[upos])
            upos += 1
            continue
        block = flat[:, offs[fpos]: offs[fpos + 1]]
        fpos += 1
        if k in udf_names:
            block = apply_udf(udf_names[k], block)
        cols.append(block)
    return np.concatenate(cols, axis=1)


def _apply_nb_conds(graph, conds, nbr, w, tt, mask):
    keep = graph.condition_mask(
        nbr.reshape(-1), _resolve_dnf(graph, conds)
    ).reshape(nbr.shape)
    keep &= mask
    return (
        np.where(keep, nbr, DEFAULT_ID),
        np.where(keep, w, 0.0).astype(np.float32),
        np.where(keep, tt, -1),
        keep,
    )


def execute_plan(graph, plan, roots, base_seed: int) -> dict:
    """Run a sub-plan against a Graph facade. Returns {alias: tagged},
    plus "_" (the last step's result) and, when the plan contains a
    ``rows`` op, "__hops". Tags — ("arr", mult, array),
    ("nb", mult, (nbr, w, tt, mask)), ("hops", mults, five per-hop
    lists) — carry the per-root row multiplicity the client merge needs
    to interleave subsets back into root order."""
    roots = np.asarray(roots, dtype=np.uint64)
    track_hops = any(step["op"] == "rows" for step in plan)
    cur = roots
    m = 1  # frontier rows per root
    last = None
    results: dict[str, tuple] = {}
    hop_ids = [cur]
    hop_w = [np.ones(len(cur), np.float32)]
    hop_tt: list = [None]  # hop-0 types cost a scatter; resolved by "rows"
    hop_mask = [cur != DEFAULT_ID]
    hop_mults = [1]

    for t, step in enumerate(plan):
        op = step["op"]
        if op == "v":
            if step.get("conds"):
                keep = graph.condition_mask(
                    cur, _resolve_dnf(graph, step["conds"])
                )
                cur = np.where(keep, cur, DEFAULT_ID)
                hop_ids[0] = cur
                hop_mask[0] = cur != DEFAULT_ID
            last = ("arr", m, cur)
        elif op == "sample_nb":
            et, n = step["et"], int(step["n"])

            def fn(sh, sub, et=et, n=n, t=t):
                return sh.sample_neighbor(
                    sub, et, n, _rng_for(sh, step_seed(base_seed, t, sh.part))
                )

            nbr, w, tt, mask, _ = graph._scatter_gather(cur, fn)
            mask = np.asarray(mask, dtype=bool)
            if step.get("conds"):
                nbr, w, tt, mask = _apply_nb_conds(
                    graph, step["conds"], nbr, w, tt, mask
                )
            last = ("nb", m, (nbr, w, tt, mask))
            cur = nbr.reshape(-1)
            m *= n
            if track_hops:
                hop_ids.append(cur)
                hop_w.append(w.reshape(-1).astype(np.float32))
                hop_tt.append(tt.reshape(-1).astype(np.int32))
                hop_mask.append(mask.reshape(-1))
                hop_mults.append(m)
        elif op == "full_nb":
            nbr, w, tt, mask, _ = graph.get_full_neighbor(
                cur, step["et"], max_degree=step["cap"],
                in_edges=bool(step.get("in_edges")),
            )
            mask = np.asarray(mask, dtype=bool)
            if step.get("conds"):
                nbr, w, tt, mask = _apply_nb_conds(
                    graph, step["conds"], nbr, w, tt, mask
                )
            last = ("nb", m, (nbr, w, tt, mask))
            cur = nbr.reshape(-1)
            if step["cap"] is not None:
                m *= int(step["cap"])
                if track_hops:
                    hop_ids.append(cur)
                    hop_w.append(w.reshape(-1).astype(np.float32))
                    hop_tt.append(tt.reshape(-1).astype(np.int32))
                    hop_mask.append(mask.reshape(-1))
                    hop_mults.append(m)
        elif op == "values":
            last = (
                "arr", m,
                _fetch_values(graph, cur, step["names"], step["udfs"]),
            )
        elif op == "label":
            last = ("arr", m, np.asarray(graph.node_type(cur)))
        elif op == "get":
            last = ("arr", m, cur)
        elif op == "has_type":
            keep = np.asarray(graph.node_type(cur)) == int(step["t"])
            cur = np.where(keep, cur, DEFAULT_ID)
            last = ("arr", m, cur)
        elif op == "degree":
            last = (
                "arr", m,
                np.asarray(graph.degree_sum(cur, step.get("et")), np.int64),
            )
        elif op == "order_by":
            kind, mm, (nbr, w, tt, mask) = last
            key = w if step["key"] == "weight" else nbr
            order = np.argsort(
                -key if step["desc"] else key, axis=1, kind="stable"
            )
            take = np.take_along_axis
            last = (kind, mm, (
                take(nbr, order, 1), take(w, order, 1),
                take(tt, order, 1), take(mask, order, 1),
            ))
            cur = last[2][0].reshape(-1)
        elif op == "as":
            pass  # capture handled below
        elif op == "rows":
            all_rows = np.asarray(
                graph.lookup_rows(np.concatenate(hop_ids)), np.int64
            )
            offs = _offsets([len(h) for h in hop_ids])
            hop_rows = [
                all_rows[offs[i]: offs[i + 1]] for i in range(len(hop_ids))
            ]
            hop_tt[0] = np.asarray(graph.node_type(hop_ids[0]), np.int32)
            results["__hops"] = ("hops", list(hop_mults), (
                hop_ids, hop_w, list(hop_tt), hop_mask, hop_rows,
            ))
        else:
            raise ValueError(f"unknown plan op {op!r}")
        if step.get("as"):
            results[str(step["as"])] = last
        if op == "as":
            results[str(step["name"])] = last
    results["_"] = last
    return results


# ---------------------------------------------------------------------------
# wire packing (exec_plan response)
# ---------------------------------------------------------------------------


def pack_results(results: dict) -> list:
    """Tagged results dict → flat wire values: [manifest_json, payload...].
    Bool arrays survive as uint8 on the wire; unpack restores them by
    position convention (nb[3] and hops mask list)."""
    manifest = []
    payload: list = []
    for name, (kind, mult, value) in results.items():
        if kind == "arr":
            manifest.append([name, kind, mult, 1])
            payload.append(value)
        elif kind == "nb":
            manifest.append([name, kind, mult, 4])
            payload.extend(value)
        elif kind == "hops":
            manifest.append([name, kind, mult, 5])
            payload.extend(list(v) for v in value)  # 5 lists of arrays
        else:
            raise ValueError(f"cannot pack result kind {kind!r}")
    return [json.dumps(manifest)] + payload


def unpack_results(values: list) -> dict:
    manifest = json.loads(values[0])
    out = {}
    pos = 1
    for name, kind, mult, n in manifest:
        if kind == "arr":
            out[name] = (kind, mult, values[pos])
        elif kind == "nb":
            nbr, w, tt, mask = values[pos: pos + 4]
            out[name] = (kind, mult, (nbr, w, tt, np.asarray(mask, bool)))
        else:  # hops
            ids, w, tt, mask, rows = values[pos: pos + 5]
            out[name] = (kind, mult, (
                list(ids), list(w), list(tt),
                [np.asarray(mk, bool) for mk in mask],
                [np.asarray(r, np.int64) for r in rows],
            ))
        pos += n
    return out


# ---------------------------------------------------------------------------
# client entry: SPLIT → exec_plan per shard (or per-op) → MERGE
# ---------------------------------------------------------------------------


def _fill_like(a: np.ndarray, n_rows: int) -> np.ndarray:
    """Output template with the same fill convention as _scatter_gather:
    DEFAULT_ID for u64 ids, -1 for int types/rows, zeros else."""
    out = np.zeros((n_rows,) + a.shape[1:], dtype=a.dtype)
    if a.dtype == np.uint64:
        out[:] = DEFAULT_ID
    elif a.dtype in (np.int32, np.int64):
        out[:] = -1
    return out


def _merge_arr(parts, subsets, n_roots, mult):
    """Interleave per-subset row blocks back into root order: root i's
    rows live at [i*mult, (i+1)*mult)."""
    template = next(p for p in parts if p is not None)
    out = _fill_like(template, n_roots * mult)
    if template.dtype == np.bool_:
        out[:] = False
    for part, idx in zip(parts, subsets):
        if part is None or not len(idx):
            continue
        dest = (idx[:, None] * mult + np.arange(mult)).reshape(-1)
        out[dest] = part
    return out


def _merge_nb(parts, subsets, n_roots, mult):
    caps = [p[0].shape[1] for p in parts if p is not None]
    cap = max(caps)
    fills = (DEFAULT_ID, np.float32(0.0), np.int32(-1), False)
    merged = []
    for j, fill in enumerate(fills):
        template = next(p for p in parts if p is not None)[j]
        out = np.full(
            (n_roots * mult, cap), fill, dtype=template.dtype
        )
        for part, idx in zip(parts, subsets):
            if part is None or not len(idx):
                continue
            a = part[j]
            dest = (idx[:, None] * mult + np.arange(mult)).reshape(-1)
            out[dest, : a.shape[1]] = a
        merged.append(out)
    return tuple(merged)


def _merge_results(per_subset, subsets, n_roots) -> dict:
    first = next(r for r in per_subset if r is not None)
    out = {}
    for name, (kind, mult, _) in first.items():
        parts = [r[name][2] if r is not None else None for r in per_subset]
        if kind == "arr":
            out[name] = _merge_arr(parts, subsets, n_roots, mult)
        elif kind == "nb":
            out[name] = _merge_nb(parts, subsets, n_roots, mult)
        else:  # hops: merge each per-hop array independently
            mults = first[name][1]
            cols = []
            for j in range(5):
                cols.append([
                    _merge_arr(
                        [p[j][h] if p is not None else None for p in parts],
                        subsets, n_roots, mults[h],
                    )
                    for h in range(len(mults))
                ])
            out[name] = tuple(cols)
    return out


def _untag(results: dict) -> dict:
    out = {}
    for name, (kind, _, value) in results.items():
        out[name] = value if kind != "hops" else tuple(list(v) for v in value)
    return out


def run_plan(graph, plan, roots, seed: int, fused: bool | None = None) -> dict:
    """Execute a plan over a (possibly remote) Graph: SPLIT roots by
    owner, one exec_plan RPC per non-empty shard (pipelined through each
    shard's in-flight executor), MERGE per-alias results in root order.
    Per-op mode (fused=False / EULER_TPU_FUSED_PLAN=0) drives the same
    per-subset sub-plans client-side with the same seeds — bit-identical
    output, ~L×P round trips instead of P."""
    roots = np.asarray(roots, dtype=np.uint64)
    if fused is None:
        fused = _fused_enabled()
    shards = getattr(graph, "shards", None)
    remote = shards is not None and all(hasattr(s, "call") for s in shards)
    num_shards = getattr(graph, "num_shards", 1)
    if num_shards == 1 or len(roots) == 0:
        base = subset_seed(seed, 0)
        if fused and remote and len(roots):
            try:
                res = unpack_results(
                    shards[0].call("exec_plan", [json.dumps(plan), roots, base])
                )
            except Exception as e:
                if "unknown op" not in str(e):
                    raise
                res = execute_plan(graph, plan, roots, base)
        else:
            res = execute_plan(graph, plan, roots, base)
        return _untag(res)

    owner = (roots % np.uint64(num_shards)).astype(np.int64)
    subsets = [np.nonzero(owner == s)[0] for s in range(num_shards)]
    per_subset: list = [None] * num_shards
    if fused and remote:
        plan_json = json.dumps(plan)
        futs = [
            shards[s].submit(
                "exec_plan", [plan_json, roots[idx], subset_seed(seed, s)]
            )
            if len(idx)
            else None
            for s, idx in enumerate(subsets)
        ]
        for s, fut in enumerate(futs):
            if fut is None:
                continue
            try:
                per_subset[s] = unpack_results(fut.result())
            except Exception as e:
                if "unknown op" not in str(e):
                    raise
                # server predates exec_plan: same sub-plan, same seed,
                # driven per-op from here — identical results
                per_subset[s] = execute_plan(
                    graph, plan, roots[subsets[s]], subset_seed(seed, s)
                )
    else:
        for s, idx in enumerate(subsets):
            if len(idx):
                per_subset[s] = execute_plan(
                    graph, plan, roots[idx], subset_seed(seed, s)
                )
    return _merge_results(per_subset, subsets, len(roots))


def is_remote_graph(graph) -> bool:
    shards = getattr(graph, "shards", None)
    return bool(shards) and all(hasattr(s, "call") for s in shards)
