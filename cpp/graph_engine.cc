// euler_tpu native graph engine.
//
// The TPU-host counterpart of the reference's C++ graph core
// (euler/core/graph/graph.h:41-209, node.h:59-198, common/alias_method.h):
// mmaps the columnar tensor-dir shard format (euler_tpu/graph/format.py),
// builds O(1) alias samplers per node/edge type and per-row cumulative
// weights for O(log deg) weighted neighbor sampling, and serves batched
// queries over a fork-join thread pool. Exposed as a C ABI consumed via
// ctypes (euler_tpu/graph/native.py) — no Python in the hot loop.
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC graph_engine.cc
//        -o libeuler_tpu_engine.so -lpthread

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

using u8 = uint8_t;
using i32 = int32_t;
using i64 = int64_t;
using u64 = uint64_t;
using f32 = float;

constexpr u64 kDefaultId = ~0ull;

// ---------------------------------------------------------------- utils

struct SplitMix64 {
  u64 s;
  explicit SplitMix64(u64 seed) : s(seed) {}
  u64 next() {
    u64 z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

// fork-join parallel for over [0, n)
void ParallelFor(i64 n, i64 grain, const std::function<void(i64, i64)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  i64 nthreads = std::min<i64>(hw ? hw : 4, (n + grain - 1) / grain);
  if (nthreads <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  i64 chunk = (n + nthreads - 1) / nthreads;
  for (i64 t = 0; t < nthreads; ++t) {
    i64 lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

// O(1) weighted sampling (alias method; same contract as the reference's
// AliasMethod::Init/Next, euler/common/alias_method.h:28-42)
struct AliasTable {
  std::vector<double> prob;
  std::vector<i64> alias;
  std::vector<i64> members;  // uniform weights, subset of items (by type)
  double total = 0.0;
  i64 n_ = 0;
  bool uniform_dense = false;  // uniform weights over ALL items: O(1), 0 B

  void Build(const f32* w, const i32* types, i32 want_type, i64 n) {
    // Uniform detection first: the common unit-weight graph needs NO
    // materialized table (16 B/item otherwise — at 10^9 edges that is
    // the difference between loading and OOM).
    n_ = n;
    total = 0.0;
    prob.clear();
    alias.clear();
    members.clear();
    uniform_dense = false;
    bool uniform = true;
    f32 w0 = 0.0f;
    i64 count = 0;
    for (i64 i = 0; i < n; ++i) {
      if (want_type < 0 || types[i] == want_type) {
        if (!count) w0 = w[i];
        uniform &= (w[i] == w0);
        total += w[i];
        ++count;
      }
    }
    if (n == 0 || total <= 0) return;
    if (uniform) {
      if (count == n) {
        uniform_dense = true;
        return;
      }
      members.reserve(count);
      for (i64 i = 0; i < n; ++i)
        if (want_type < 0 || types[i] == want_type) members.push_back(i);
      return;
    }
    std::vector<double> p(n);
    for (i64 i = 0; i < n; ++i)
      p[i] = (want_type < 0 || types[i] == want_type) ? w[i] : 0.0;
    prob.assign(n, 1.0);
    alias.assign(n, 0);
    double mean = total / n;
    std::vector<i64> small, large;
    small.reserve(n);
    large.reserve(n);
    for (i64 i = 0; i < n; ++i)
      (p[i] < mean ? small : large).push_back(i);
    while (!small.empty() && !large.empty()) {
      i64 s = small.back(), l = large.back();
      small.pop_back();
      prob[s] = p[s] / mean;
      alias[s] = l;
      p[l] -= (mean - p[s]);
      if (p[l] < mean) {
        large.pop_back();
        small.push_back(l);
      }
    }
    for (i64 i : small) prob[i] = 1.0;
    for (i64 i : large) prob[i] = 1.0;
  }

  i64 Sample(SplitMix64& rng, i64 n) const {
    if (n == 0 || total <= 0) return -1;
    if (uniform_dense) {
      i64 i = (i64)(rng.uniform() * n_);
      return i >= n_ ? n_ - 1 : i;
    }
    if (!members.empty()) {
      i64 m = (i64)members.size();
      i64 i = (i64)(rng.uniform() * m);
      return members[i >= m ? m - 1 : i];
    }
    i64 i = (i64)(rng.uniform() * n);
    if (i >= n) i = n - 1;
    return rng.uniform() < prob[i] ? i : alias[i];
  }
};

// ------------------------------------------------------------- tensor dir

struct ArrayRef {
  const void* data = nullptr;
  std::vector<i64> shape;
  int code = 0;
  i64 nbytes = 0;
};

struct MappedDir {
  void* base = nullptr;
  size_t len = 0;
  std::unordered_map<std::string, ArrayRef> arrays;

  ~MappedDir() {
    if (base) munmap(base, len);
  }

  bool Load(const std::string& dir) {
    std::string bin = dir + "/tensors.bin";
    std::string idx = dir + "/tensors.idx";
    int fd = open(bin.c_str(), O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    fstat(fd, &st);
    len = st.st_size;
    base = len ? mmap(nullptr, len, PROT_READ, MAP_SHARED, fd, 0) : nullptr;
    close(fd);
    if (len && base == MAP_FAILED) {
      base = nullptr;
      return false;
    }
    FILE* f = fopen(idx.c_str(), "rb");
    if (!f) return false;
    char magic[8];
    if (fread(magic, 1, 8, f) != 8 || memcmp(magic, "EULRTPU1", 8) != 0) {
      fclose(f);
      return false;
    }
    i64 count = 0;
    fread(&count, 8, 1, f);
    for (i64 k = 0; k < count; ++k) {
      i32 name_len = 0;
      fread(&name_len, 4, 1, f);
      std::string name(name_len, '\0');
      fread(name.data(), 1, name_len, f);
      u8 code = 0, ndim = 0;
      fread(&code, 1, 1, f);
      fread(&ndim, 1, 1, f);
      ArrayRef ref;
      ref.code = code;
      ref.shape.resize(ndim);
      for (int d = 0; d < ndim; ++d) fread(&ref.shape[d], 8, 1, f);
      i64 offset = 0;
      fread(&offset, 8, 1, f);
      fread(&ref.nbytes, 8, 1, f);
      ref.data = (const char*)base + offset;
      arrays[name] = ref;
    }
    fclose(f);
    return true;
  }

  template <typename T>
  const T* Get(const std::string& name, i64* n = nullptr) const {
    auto it = arrays.find(name);
    if (it == arrays.end()) return nullptr;
    if (n) *n = it->second.shape.empty() ? 0 : it->second.shape[0];
    return (const T*)it->second.data;
  }
};

// ------------------------------------------------------------------ store

struct Csr {
  const i64* indptr = nullptr;
  const u64* dst = nullptr;
  const f32* w = nullptr;
  const i64* eidx = nullptr;
  i64 n_rows = 0;
  std::vector<double> cum;  // [nnz+1] cumulative weights (non-uniform only)
  std::vector<i32> dst_row;  // [nnz] local row of each dst (-1 off-shard);
                             // kills the per-sample id binary search.
                             // i32: shards are capped at 2^31 nodes (Init
                             // enforces), halving the per-edge overhead
  bool uniform = false;  // all weights equal → O(1) in-row sampling
  double w0 = 0.0;  // the uniform weight (RowWeight without cum)

  void BuildCum(i64 nnz) {
    uniform = true;
    w0 = nnz ? (double)w[0] : 0.0;
    for (i64 i = 0; i < nnz; ++i) uniform &= (w[i] == w[0]);
    if (uniform) {
      cum.clear();  // 8 B/edge saved on the common unit-weight graph
      return;
    }
    cum.resize(nnz + 1);
    cum[0] = 0.0;
    for (i64 i = 0; i < nnz; ++i) cum[i + 1] = cum[i] + w[i];
  }

  i64 Degree(i64 row) const { return indptr[row + 1] - indptr[row]; }
  double RowWeight(i64 row) const {
    return uniform ? w0 * (indptr[row + 1] - indptr[row])
                   : cum[indptr[row + 1]] - cum[indptr[row]];
  }
  // weighted pick of a global element index within row
  i64 SampleInRow(i64 row, SplitMix64& rng) const {
    i64 s = indptr[row], e = indptr[row + 1];
    if (s >= e) return -1;
    if (uniform) {
      i64 i = s + (i64)(rng.uniform() * (e - s));
      return i < e ? i : e - 1;
    }
    double lo = cum[s], hi = cum[e];
    double target = lo + rng.uniform() * (hi - lo);
    // binary search in cum[s..e]
    i64 a = s, b = e;
    while (a < b) {
      i64 m = (a + b) / 2;
      if (cum[m + 1] <= target)
        a = m + 1;
      else
        b = m;
    }
    return a < e ? a : e - 1;
  }
};

// Per-op timing counters (SURVEY.md §5: the host engine exports per-op
// timings; the reference has common/timmer.h). Index = Op enum below.
enum Op : int {
  kOpLookup = 0,
  kOpSampleNode,
  kOpSampleEdge,
  kOpSampleNeighbor,
  kOpGetDense,
  kOpRandomWalk,
  kOpSampleFanout,
  kOpFullNeighbor,
  kOpDegreeSum,
  kOpVarlen,
  kOpLayerwise,
  kNumOps,
};

struct OpStats {
  std::atomic<u64> calls[kNumOps] = {};
  std::atomic<u64> nanos[kNumOps] = {};
};

struct ScopedTimer {
  OpStats& st;
  int op;
  std::chrono::steady_clock::time_point t0;
  ScopedTimer(OpStats& s, int o) : st(s), op(o) {
    t0 = std::chrono::steady_clock::now();
  }
  ~ScopedTimer() {
    auto dt = std::chrono::steady_clock::now() - t0;
    st.calls[op].fetch_add(1, std::memory_order_relaxed);
    st.nanos[op].fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(dt).count(),
        std::memory_order_relaxed);
  }
};

struct Store {
  MappedDir dir;
  OpStats stats;
  const u64* node_ids = nullptr;
  const i32* node_types = nullptr;
  const f32* node_weights = nullptr;
  i64 num_nodes = 0;
  i64 num_edge_types = 0;
  i64 num_node_types = 0;
  std::vector<Csr> adj;
  std::vector<Csr> inadj;  // in-edge CSRs (empty when shard lacks them)
  std::vector<AliasTable> node_samplers;  // per type + [last] all
  const u64* edge_src = nullptr;
  const u64* edge_dst = nullptr;
  const i32* edge_types = nullptr;
  const f32* edge_weights = nullptr;
  i64 num_edges = 0;
  std::vector<AliasTable> edge_samplers;

  i64 Lookup(u64 id) const {
    i64 lo = 0, hi = num_nodes;
    while (lo < hi) {
      i64 m = (lo + hi) / 2;
      if (node_ids[m] < id)
        lo = m + 1;
      else
        hi = m;
    }
    return (lo < num_nodes && node_ids[lo] == id) ? lo : -1;
  }

  bool Init(const std::string& path, i64 n_node_types, i64 n_edge_types) {
    if (!dir.Load(path)) return false;
    node_ids = dir.Get<u64>("node_ids", &num_nodes);
    node_types = dir.Get<i32>("node_types");
    node_weights = dir.Get<f32>("node_weights");
    edge_src = dir.Get<u64>("edge_src", &num_edges);
    edge_dst = dir.Get<u64>("edge_dst");
    edge_types = dir.Get<i32>("edge_types");
    edge_weights = dir.Get<f32>("edge_weights");
    if (!node_ids || !node_types || !node_weights) return false;
    if (num_nodes >= (i64)1 << 31) return false;  // i32 dst_row contract
    num_node_types = n_node_types;
    num_edge_types = n_edge_types;
    adj.resize(num_edge_types);
    for (i64 t = 0; t < num_edge_types; ++t) {
      std::string tag = "adj_" + std::to_string(t);
      Csr& c = adj[t];
      c.indptr = dir.Get<i64>(tag + "_indptr");
      i64 nnz = 0;
      c.dst = dir.Get<u64>(tag + "_dst", &nnz);
      c.w = dir.Get<f32>(tag + "_w");
      c.eidx = dir.Get<i64>(tag + "_eidx");
      c.n_rows = num_nodes;
      if (!c.indptr || (nnz && (!c.dst || !c.w))) return false;
      c.BuildCum(nnz);
    }
    if (dir.Get<i64>("inadj_0_indptr")) {
      inadj.resize(num_edge_types);
      for (i64 t = 0; t < num_edge_types; ++t) {
        std::string tag = "inadj_" + std::to_string(t);
        Csr& c = inadj[t];
        c.indptr = dir.Get<i64>(tag + "_indptr");
        i64 nnz = 0;
        c.dst = dir.Get<u64>(tag + "_dst", &nnz);
        c.w = dir.Get<f32>(tag + "_w");
        c.eidx = dir.Get<i64>(tag + "_eidx");
        c.n_rows = num_nodes;
        if (!c.indptr || (nnz && (!c.dst || !c.w))) {
          inadj.clear();
          break;
        }
        c.BuildCum(nnz);
      }
    }
    // pre-resolve each adjacency dst to its local row once, so sampling
    // paths never pay the per-sample id binary search
    for (auto* set : {&adj, &inadj}) {
      for (Csr& c : *set) {
        if (!c.indptr) continue;
        i64 nnz = c.indptr[num_nodes];
        c.dst_row.resize(nnz);
        ParallelFor(nnz, 65536, [&](i64 lo, i64 hi) {
          for (i64 i = lo; i < hi; ++i) c.dst_row[i] = (i32)Lookup(c.dst[i]);
        });
      }
    }
    node_samplers.resize(num_node_types + 1);
    for (i64 t = 0; t < num_node_types; ++t)
      node_samplers[t].Build(node_weights, node_types, (i32)t, num_nodes);
    node_samplers[num_node_types].Build(node_weights, node_types, -1,
                                        num_nodes);
    edge_samplers.resize(num_edge_types + 1);
    for (i64 t = 0; t < num_edge_types; ++t)
      edge_samplers[t].Build(edge_weights, edge_types, (i32)t, num_edges);
    edge_samplers[num_edge_types].Build(edge_weights, edge_types, -1,
                                        num_edges);
    return true;
  }
};

// One weighted neighbor draw for `row`: weighted type pick over `tot`
// (catch-all last type), then an in-row cumulative-weight sample. Shared by
// the per-hop and fused fanout kernels so their distributions stay in
// lockstep. Returns {nullptr, -1, -1} when the row is missing or empty.
struct NeighborPick {
  const Csr* csr;
  i64 el;
  i32 type;
};

inline NeighborPick PickNeighbor(const Store* s, i64 row, const i32* types,
                                 i64 ntypes, const double* tot, double total,
                                 SplitMix64& rng) {
  if (row < 0 || total <= 0) return {nullptr, -1, -1};
  double u = rng.uniform() * total;
  i64 pick = 0;
  double acc = 0.0;
  for (; pick < ntypes - 1; ++pick) {
    acc += tot[pick];
    if (u < acc) break;
  }
  const Csr& c = s->adj[types[pick]];
  i64 el = c.SampleInRow(row, rng);
  if (el < 0) return {nullptr, -1, -1};
  return {&c, el, types[pick]};
}

}  // namespace

// ---------------------------------------------------------------- C ABI

extern "C" {

void* etpu_load(const char* dir, i64 num_node_types, i64 num_edge_types) {
  auto* s = new Store();
  if (!s->Init(dir, num_node_types, num_edge_types)) {
    delete s;
    return nullptr;
  }
  return s;
}

void etpu_free(void* h) { delete (Store*)h; }

i64 etpu_num_nodes(void* h) { return ((Store*)h)->num_nodes; }
i64 etpu_num_edges(void* h) { return ((Store*)h)->num_edges; }

void etpu_lookup(void* h, const u64* ids, i64 n, i64* rows) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpLookup);
  ParallelFor(n, 4096, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) rows[i] = s->Lookup(ids[i]);
  });
}

void etpu_sample_node(void* h, i64 count, i32 node_type, u64 seed, u64* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpSampleNode);
  i64 ti = node_type < 0 ? s->num_node_types : node_type;
  const AliasTable& at = s->node_samplers[ti];
  ParallelFor(count, 8192, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0x517cc1b727220a95ull * (u64)(lo + 1)));
    for (i64 i = lo; i < hi; ++i) {
      i64 r = at.Sample(rng, s->num_nodes);
      out[i] = r < 0 ? kDefaultId : s->node_ids[r];
    }
  });
}

void etpu_sample_edge(void* h, i64 count, i32 edge_type, u64 seed, u64* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpSampleEdge);
  i64 ti = edge_type < 0 ? s->num_edge_types : edge_type;
  const AliasTable& at = s->edge_samplers[ti];
  ParallelFor(count, 8192, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0x9e3779b97f4a7c15ull * (u64)(lo + 1)));
    for (i64 i = lo; i < hi; ++i) {
      i64 r = at.Sample(rng, s->num_edges);
      if (r < 0) {
        out[3 * i] = out[3 * i + 1] = out[3 * i + 2] = kDefaultId;
      } else {
        out[3 * i] = s->edge_src[r];
        out[3 * i + 1] = s->edge_dst[r];
        out[3 * i + 2] = (u64)s->edge_types[r];
      }
    }
  });
}

// Weighted neighbor sampling across edge types. Outputs shaped [n, count].
void etpu_sample_neighbor(void* h, const u64* ids, i64 n, const i32* types,
                          i64 ntypes, i64 count, u64 seed, u64* nbr, f32* w,
                          i32* tt, u8* mask, i64* eidx) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpSampleNeighbor);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 256, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0x2545f4914f6cdd1dull * (u64)(lo + 1)));
    std::vector<double> tot(ntypes);
    for (i64 i = lo; i < hi; ++i) {
      i64 row = s->Lookup(ids[i]);
      double total = 0.0;
      for (i64 k = 0; k < ntypes; ++k) {
        tot[k] = row < 0 ? 0.0 : s->adj[types[k]].RowWeight(row);
        total += tot[k];
      }
      for (i64 c = 0; c < count; ++c) {
        i64 o = i * count + c;
        nbr[o] = kDefaultId;
        w[o] = 0.f;
        tt[o] = -1;
        mask[o] = 0;
        eidx[o] = -1;
        NeighborPick p =
            PickNeighbor(s, row, types, ntypes, tot.data(), total, rng);
        if (p.el < 0) continue;
        nbr[o] = p.csr->dst[p.el];
        w[o] = p.csr->w[p.el];
        tt[o] = p.type;
        mask[o] = 1;
        eidx[o] = p.csr->eidx ? p.csr->eidx[p.el] : -1;
      }
    }
  });
}

// Dense feature fetch: rows resolved per id; missing ids → zeros.
void etpu_get_dense(void* h, const u64* ids, i64 n, i64 fid, i64 dim,
                    f32* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpGetDense);
  std::string name = "nf_dense_" + std::to_string(fid);
  i64 rows_n = 0;
  const f32* table = s->dir.Get<f32>(name, &rows_n);
  if (!table) {
    memset(out, 0, sizeof(f32) * n * dim);
    return;
  }
  ParallelFor(n, 1024, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      i64 row = s->Lookup(ids[i]);
      if (row < 0)
        memset(out + i * dim, 0, sizeof(f32) * dim);
      else
        memcpy(out + i * dim, table + row * dim, sizeof(f32) * dim);
    }
  });
}

// Fused multi-hop fanout (one call per batch instead of one per hop).
// Hop h occupies n*prod(counts[:h]) slots, regions appended in hop order
// (hop 0 echoes the roots). rows_out carries each slot's local store row
// (-1 when missing/padded) so callers can feed device feature caches
// without a second lookup pass.
void etpu_sample_fanout(void* h, const u64* roots, i64 n, const i32* types,
                        i64 ntypes, const i64* counts, i64 num_hops, u64 seed,
                        u64* ids_out, i64* rows_out, f32* w_out, i32* tt_out,
                        u8* mask_out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpSampleFanout);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  // hop 0: echo roots, resolve rows
  ParallelFor(n, 2048, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      i64 row = roots[i] == kDefaultId ? -1 : s->Lookup(roots[i]);
      ids_out[i] = roots[i];
      rows_out[i] = row;
      w_out[i] = 1.f;
      tt_out[i] = row < 0 ? -1 : s->node_types[row];
      mask_out[i] = row >= 0;
    }
  });
  i64 off = 0, width = n;
  for (i64 hop = 0; hop < num_hops; ++hop) {
    i64 cnt = counts[hop];
    i64 next_off = off + width;
    const i64* frow = rows_out + off;
    u64* nbr = ids_out + next_off;
    i64* nrow = rows_out + next_off;
    f32* nw = w_out + next_off;
    i32* ntt = tt_out + next_off;
    u8* nm = mask_out + next_off;
    ParallelFor(width, 256, [&](i64 lo, i64 hi) {
      SplitMix64 rng(seed ^ (0x94d049bb133111ebull * (u64)(hop + 1)) ^
                     (0x2545f4914f6cdd1dull * (u64)(lo + 1)));
      std::vector<double> tot(ntypes);
      for (i64 i = lo; i < hi; ++i) {
        i64 row = frow[i];
        double total = 0.0;
        for (i64 k = 0; k < ntypes; ++k) {
          tot[k] = row < 0 ? 0.0 : s->adj[types[k]].RowWeight(row);
          total += tot[k];
        }
        for (i64 c = 0; c < cnt; ++c) {
          i64 o = i * cnt + c;
          nbr[o] = kDefaultId;
          nrow[o] = -1;
          nw[o] = 0.f;
          ntt[o] = -1;
          nm[o] = 0;
          NeighborPick p =
              PickNeighbor(s, row, types, ntypes, tot.data(), total, rng);
          if (p.el < 0) continue;
          nbr[o] = p.csr->dst[p.el];
          nrow[o] = p.csr->dst_row[p.el];
          nw[o] = p.csr->w[p.el];
          ntt[o] = p.type;
          nm[o] = 1;
        }
      }
    });
    off = next_off;
    width *= cnt;
  }
}

// Per-op stats: out[0..kNumOps) = call counts, out[kNumOps..2*kNumOps) = ns.
void etpu_stats(void* h, u64* out) {
  auto* s = (Store*)h;
  for (int op = 0; op < kNumOps; ++op) {
    out[op] = s->stats.calls[op].load(std::memory_order_relaxed);
    out[kNumOps + op] = s->stats.nanos[op].load(std::memory_order_relaxed);
  }
}

void etpu_reset_stats(void* h) {
  auto* s = (Store*)h;
  for (int op = 0; op < kNumOps; ++op) {
    s->stats.calls[op].store(0, std::memory_order_relaxed);
    s->stats.nanos[op].store(0, std::memory_order_relaxed);
  }
}

// Dense feature fetch by pre-resolved store rows (-1 → zeros). Skips the
// per-id binary search when the caller already has rows (e.g. from
// etpu_sample_fanout's rows_out).
void etpu_get_dense_rows(void* h, const i64* rows, i64 n, i64 fid, i64 dim,
                         f32* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpGetDense);
  std::string name = "nf_dense_" + std::to_string(fid);
  i64 rows_n = 0;
  const f32* table = s->dir.Get<f32>(name, &rows_n);
  if (!table) {
    memset(out, 0, sizeof(f32) * n * dim);
    return;
  }
  ParallelFor(n, 2048, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      if (rows[i] < 0 || rows[i] >= rows_n)
        memset(out + i * dim, 0, sizeof(f32) * dim);
      else
        memcpy(out + i * dim, table + rows[i] * dim, sizeof(f32) * dim);
    }
  });
}

// Uniform/weighted random walk (p=q=1 fast path). Output [n, len+1].
void etpu_random_walk(void* h, const u64* ids, i64 n, const i32* types,
                      i64 ntypes, i64 walk_len, u64 seed, u64* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpRandomWalk);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 256, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0xd6e8feb86659fd93ull * (u64)(lo + 1)));
    std::vector<double> tot(ntypes);
    for (i64 i = lo; i < hi; ++i) {
      u64 cur = ids[i];
      out[i * (walk_len + 1)] = cur;
      for (i64 step = 1; step <= walk_len; ++step) {
        u64 nxt = kDefaultId;
        if (cur != kDefaultId) {
          i64 row = s->Lookup(cur);
          if (row >= 0) {
            double total = 0.0;
            for (i64 k = 0; k < ntypes; ++k) {
              tot[k] = s->adj[types[k]].RowWeight(row);
              total += tot[k];
            }
            if (total > 0) {
              double u = rng.uniform() * total;
              i64 pick = 0;
              double acc = 0.0;
              for (; pick < ntypes - 1; ++pick) {
                acc += tot[pick];
                if (u < acc) break;
              }
              i64 el = s->adj[types[pick]].SampleInRow(row, rng);
              if (el >= 0) nxt = s->adj[types[pick]].dst[el];
            }
          }
        }
        out[i * (walk_len + 1) + step] = nxt;
        cur = nxt;
      }
    }
  });
}

// -------- extended query families (node.h:82-145 parity: full/top-k
// neighbors, degrees, in-edges, varlen features, layerwise sampling) -----

// CSR set for a direction; nullptr when the shard has no in-edge CSRs.
static const std::vector<Csr>* CsrSet(const Store* s, u8 in_edges) {
  if (!in_edges) return &s->adj;
  return s->inadj.empty() ? nullptr : &s->inadj;
}

void etpu_degree_sum(void* h, const u64* ids, i64 n, const i32* types,
                     i64 ntypes, u8 in_edges, i64* out) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpDegreeSum);
  const std::vector<Csr>* set = CsrSet(s, in_edges);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 2048, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      i64 row = s->Lookup(ids[i]);
      i64 d = 0;
      if (row >= 0 && set)
        for (i64 k = 0; k < ntypes; ++k) d += (*set)[types[k]].Degree(row);
      out[i] = d;
    }
  });
}

// Padded full adjacency [n, cap]; sort_mode: 0 storage order, 1 by id asc,
// 2 by weight desc (both stable, invalid slots last).
void etpu_full_neighbor(void* h, const u64* ids, i64 n, const i32* types,
                        i64 ntypes, i64 cap, u8 in_edges, i32 sort_mode,
                        u64* nbr, f32* w, i32* tt, u8* mask, i64* eidx) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpFullNeighbor);
  const std::vector<Csr>* set = CsrSet(s, in_edges);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 64, [&](i64 lo, i64 hi) {
    std::vector<i64> order;
    for (i64 i = lo; i < hi; ++i) {
      u64* rn = nbr + i * cap;
      f32* rw = w + i * cap;
      i32* rt = tt + i * cap;
      u8* rm = mask + i * cap;
      i64* re = eidx + i * cap;
      for (i64 c = 0; c < cap; ++c) {
        rn[c] = kDefaultId;
        rw[c] = 0.f;
        rt[c] = -1;
        rm[c] = 0;
        re[c] = -1;
      }
      i64 row = s->Lookup(ids[i]);
      if (row < 0 || !set) continue;
      i64 col = 0;
      for (i64 k = 0; k < ntypes && col < cap; ++k) {
        const Csr& c = (*set)[types[k]];
        for (i64 el = c.indptr[row]; el < c.indptr[row + 1] && col < cap;
             ++el, ++col) {
          rn[col] = c.dst[el];
          rw[col] = c.w[el];
          rt[col] = types[k];
          rm[col] = 1;
          re[col] = c.eidx ? c.eidx[el] : -1;
        }
      }
      if (sort_mode && col > 1) {
        order.resize(col);
        for (i64 j = 0; j < col; ++j) order[j] = j;
        if (sort_mode == 1)
          std::stable_sort(order.begin(), order.end(),
                           [&](i64 a, i64 b) { return rn[a] < rn[b]; });
        else
          std::stable_sort(order.begin(), order.end(),
                           [&](i64 a, i64 b) { return rw[a] > rw[b]; });
        std::vector<u64> tn(col);
        std::vector<f32> tw(col);
        std::vector<i32> ttv(col);
        std::vector<i64> te(col);
        for (i64 j = 0; j < col; ++j) {
          tn[j] = rn[order[j]];
          tw[j] = rw[order[j]];
          ttv[j] = rt[order[j]];
          te[j] = re[order[j]];
        }
        memcpy(rn, tn.data(), sizeof(u64) * col);
        memcpy(rw, tw.data(), sizeof(f32) * col);
        memcpy(rt, ttv.data(), sizeof(i32) * col);
        memcpy(re, te.data(), sizeof(i64) * col);
      }
    }
  });
}

// Variable-length (sparse u64 / binary u8) feature plumbing. Rows are
// pre-resolved store rows (node or edge space); kind 0 = sparse, 1 = binary.
static bool VarlenArrays(Store* s, u8 node, i32 kind, i64 fid,
                         const i64** indptr, const u8** values_u8,
                         const u64** values_u64, i64* nrows) {
  std::string base = std::string(node ? "nf_" : "ef_") +
                     (kind == 0 ? "sparse_" : "bin_") + std::to_string(fid);
  const i64* ip = s->dir.Get<i64>(base + "_indptr", nrows);
  if (!ip) return false;
  *indptr = ip;
  if (kind == 0)
    *values_u64 = s->dir.Get<u64>(base + "_values");
  else
    *values_u8 = s->dir.Get<u8>(base + "_values");
  *nrows -= 1;  // indptr has nrows+1 entries
  return true;
}

void etpu_varlen_lens(void* h, const i64* rows, i64 n, u8 node, i32 kind,
                      i64 fid, i64* lens) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpVarlen);
  const i64* indptr = nullptr;
  const u8* vu8 = nullptr;
  const u64* vu64 = nullptr;
  i64 nrows = 0;
  if (!VarlenArrays(s, node, kind, fid, &indptr, &vu8, &vu64, &nrows)) {
    memset(lens, 0, sizeof(i64) * n);
    return;
  }
  ParallelFor(n, 4096, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i)
      lens[i] = (rows[i] < 0 || rows[i] >= nrows)
                    ? 0
                    : indptr[rows[i] + 1] - indptr[rows[i]];
  });
}

void etpu_varlen_gather_u64(void* h, const i64* rows, i64 n, u8 node,
                            i32 kind, i64 fid, i64 cap, u64* vals, u8* mask) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpVarlen);
  memset(vals, 0, sizeof(u64) * n * cap);
  memset(mask, 0, sizeof(u8) * n * cap);
  const i64* indptr = nullptr;
  const u8* vu8 = nullptr;
  const u64* vu64 = nullptr;
  i64 nrows = 0;
  if (!VarlenArrays(s, node, kind, fid, &indptr, &vu8, &vu64, &nrows) ||
      !vu64)
    return;
  ParallelFor(n, 1024, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      if (rows[i] < 0 || rows[i] >= nrows) continue;
      i64 s0 = indptr[rows[i]];
      i64 len = std::min(indptr[rows[i] + 1] - s0, cap);
      for (i64 j = 0; j < len; ++j) {
        vals[i * cap + j] = vu64[s0 + j];
        mask[i * cap + j] = 1;
      }
    }
  });
}

void etpu_varlen_gather_u8(void* h, const i64* rows, i64 n, u8 node, i32 kind,
                           i64 fid, i64 cap, u8* vals) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpVarlen);
  memset(vals, 0, sizeof(u8) * n * cap);
  const i64* indptr = nullptr;
  const u8* vu8 = nullptr;
  const u64* vu64 = nullptr;
  i64 nrows = 0;
  if (!VarlenArrays(s, node, kind, fid, &indptr, &vu8, &vu64, &nrows) || !vu8)
    return;
  ParallelFor(n, 1024, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      if (rows[i] < 0 || rows[i] >= nrows) continue;
      i64 s0 = indptr[rows[i]];
      i64 len = std::min(indptr[rows[i] + 1] - s0, cap);
      memcpy(vals + i * cap, vu8 + s0, len);
    }
  });
}

// LADIES-style layerwise sampling (sample_layer_op.cc:83 parity): one
// shared candidate set per batch, sampled ∝ total incident weight, plus the
// batch→layer adjacency restricted to the sampled candidates.
void etpu_layerwise(void* h, const u64* ids, i64 n, const i32* types,
                    i64 ntypes, i64 count, u64 seed, u64* layer, f32* adj,
                    u8* lmask) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpLayerwise);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  for (i64 j = 0; j < count; ++j) {
    layer[j] = kDefaultId;
    lmask[j] = 0;
  }
  memset(adj, 0, sizeof(f32) * n * count);
  // candidate weights: sum of incident edge weight from the whole batch
  std::unordered_map<u64, double> cand;
  std::vector<i64> rowv(n);
  for (i64 i = 0; i < n; ++i) {
    rowv[i] = s->Lookup(ids[i]);
    if (rowv[i] < 0) continue;
    for (i64 k = 0; k < ntypes; ++k) {
      const Csr& c = s->adj[types[k]];
      for (i64 el = c.indptr[rowv[i]]; el < c.indptr[rowv[i] + 1]; ++el)
        cand[c.dst[el]] += c.w[el];
    }
  }
  if (cand.empty()) return;
  std::vector<u64> uniq;
  uniq.reserve(cand.size());
  for (auto& kv : cand) uniq.push_back(kv.first);
  std::sort(uniq.begin(), uniq.end());
  std::vector<double> cum(uniq.size() + 1, 0.0);
  for (size_t j = 0; j < uniq.size(); ++j)
    cum[j + 1] = cum[j] + cand[uniq[j]];
  // `count` weighted draws with replacement, then dedupe (ascending)
  SplitMix64 rng(seed ^ 0xa0761d6478bd642full);
  std::vector<u64> drawn;
  drawn.reserve(count);
  for (i64 d = 0; d < count; ++d) {
    double target = rng.uniform() * cum.back();
    size_t a = 0, b = uniq.size();
    while (a < b) {
      size_t m = (a + b) / 2;
      if (cum[m + 1] <= target)
        a = m + 1;
      else
        b = m;
    }
    drawn.push_back(uniq[std::min(a, uniq.size() - 1)]);
  }
  std::sort(drawn.begin(), drawn.end());
  drawn.erase(std::unique(drawn.begin(), drawn.end()), drawn.end());
  i64 klen = (i64)drawn.size();
  for (i64 j = 0; j < klen; ++j) {
    layer[j] = drawn[j];
    lmask[j] = 1;
  }
  // batch → layer adjacency over the sampled (sorted) candidate set
  ParallelFor(n, 128, [&](i64 lo, i64 hi) {
    for (i64 i = lo; i < hi; ++i) {
      if (rowv[i] < 0) continue;
      for (i64 k = 0; k < ntypes; ++k) {
        const Csr& c = s->adj[types[k]];
        for (i64 el = c.indptr[rowv[i]]; el < c.indptr[rowv[i] + 1]; ++el) {
          u64 d = c.dst[el];
          auto it = std::lower_bound(drawn.begin(), drawn.end(), d);
          if (it != drawn.end() && *it == d)
            adj[i * count + (it - drawn.begin())] += c.w[el];
        }
      }
    }
  });
}

// Directional weighted neighbor sampling (in_edges=1 draws from in-CSRs).
// Lean leaf sampling for the distributed fanout hot path: neighbor ids,
// validity, and the PRE-RESOLVED local row of each picked dst (from the
// load-time dst_row cache; -1 when the dst lives on another shard). Skips
// the weight/type/edge-id outputs entirely — the lean wire rebuilds unit
// weights on device, so shipping them is pure coordinator CPU waste.
void etpu_sample_neighbor_rows(void* h, const u64* ids, i64 n,
                               const i32* types, i64 ntypes, i64 count,
                               u64 seed, u64* nbr, u8* mask, i64* nrow) {
  auto* s = (Store*)h;
  ScopedTimer timer(s->stats, kOpSampleNeighbor);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 256, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0x2545f4914f6cdd1dull * (u64)(lo + 1)));
    std::vector<double> tot(ntypes);
    for (i64 i = lo; i < hi; ++i) {
      i64 row = s->Lookup(ids[i]);
      double total = 0.0;
      for (i64 k = 0; k < ntypes; ++k) {
        tot[k] = row < 0 ? 0.0 : s->adj[types[k]].RowWeight(row);
        total += tot[k];
      }
      for (i64 c = 0; c < count; ++c) {
        i64 o = i * count + c;
        nbr[o] = kDefaultId;
        mask[o] = 0;
        nrow[o] = -1;
        NeighborPick p =
            PickNeighbor(s, row, types, ntypes, tot.data(), total, rng);
        if (p.el < 0) continue;
        nbr[o] = p.csr->dst[p.el];
        mask[o] = 1;
        nrow[o] = p.csr->dst_row[p.el];
      }
    }
  });
}

void etpu_sample_neighbor_dir(void* h, const u64* ids, i64 n,
                              const i32* types, i64 ntypes, i64 count,
                              u8 in_edges, u64 seed, u64* nbr, f32* w,
                              i32* tt, u8* mask, i64* eidx) {
  auto* s = (Store*)h;
  if (!in_edges) {
    etpu_sample_neighbor(h, ids, n, types, ntypes, count, seed, nbr, w, tt,
                         mask, eidx);
    return;
  }
  ScopedTimer timer(s->stats, kOpSampleNeighbor);
  const std::vector<Csr>* set = CsrSet(s, 1);
  std::vector<i32> all_types;
  if (ntypes == 0) {
    for (i64 t = 0; t < s->num_edge_types; ++t) all_types.push_back((i32)t);
    types = all_types.data();
    ntypes = all_types.size();
  }
  ParallelFor(n, 256, [&](i64 lo, i64 hi) {
    SplitMix64 rng(seed ^ (0x8bb84b93962eacc9ull * (u64)(lo + 1)));
    std::vector<double> tot(ntypes);
    for (i64 i = lo; i < hi; ++i) {
      i64 row = s->Lookup(ids[i]);
      double total = 0.0;
      for (i64 k = 0; k < ntypes; ++k) {
        tot[k] = (row < 0 || !set) ? 0.0 : (*set)[types[k]].RowWeight(row);
        total += tot[k];
      }
      for (i64 c = 0; c < count; ++c) {
        i64 o = i * count + c;
        nbr[o] = kDefaultId;
        w[o] = 0.f;
        tt[o] = -1;
        mask[o] = 0;
        eidx[o] = -1;
        if (row < 0 || !set || total <= 0) continue;
        double u = rng.uniform() * total;
        i64 pick = 0;
        double acc = 0.0;
        for (; pick < ntypes - 1; ++pick) {
          acc += tot[pick];
          if (u < acc) break;
        }
        const Csr& cs = (*set)[types[pick]];
        i64 el = cs.SampleInRow(row, rng);
        if (el < 0) continue;
        nbr[o] = cs.dst[el];
        w[o] = cs.w[el];
        tt[o] = types[pick];
        mask[o] = 1;
        eidx[o] = cs.eidx ? cs.eidx[el] : -1;
      }
    }
  });
}

}  // extern "C"
