"""Headline benchmark: sampled edges/sec training GraphSAGE on one chip.

Trains supervised GraphSAGE (fanout sampling + mean-aggregator convs) on a
synthetic random graph. On an accelerator the local leg samples ON DEVICE
by default (DeviceSageFlow: HBM-resident adjacency, per-step PRNG keys,
zero wire bytes); the CPU fallback defaults to the host path (sampling on
prefetch worker threads + lean int32-rows wire — faster there, where
traced sampling would share the cores with model compute). The remote leg
always exercises the host wire. EULER_BENCH_DEVICE_FLOW=1/0 forces either
path on any platform. Metric matches the north star in BASELINE.json:
sampled edges/sec/chip (target 2M on v5e).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N/2e6}

Robustness: the TPU backend is warmed up on the MAIN thread before any
prefetch worker can touch JAX (round-1 failure mode: concurrent first-touch
init from worker threads). Warm-up probes run in short-lived subprocesses so
a *hanging* backend init is survivable, with bounded retries; if the
accelerator never comes up the bench re-execs itself on CPU and still emits
its JSON line (with "backend" noting the fallback). Any exception in the run
itself also emits the JSON line (value 0, "error" field) rather than dying
silently.

Leg ordering (VERDICT r3 #1): the LOCAL leg runs first and emits its JSON
line immediately, so an external timeout during the remote leg can never
void the artifact. The remote leg then runs under an internal wall-clock
budget (EULER_BENCH_REMOTE_BUDGET, default 420s) enforced by a watchdog
thread that force-emits partial results and exits 0 — hang-proof even if
the main thread is stuck in a blocked C call. The final line re-emits the
local headline (with remote_edges_per_sec attached when available) so both
first-line and last-line parsers see the verified local number.

Usage: python bench.py [--smoke] [--bf16]   (--smoke: tiny sizes, forced CPU)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

SMOKE = "--smoke" in sys.argv
BF16 = "--bf16" in sys.argv
CPU_FALLBACK = "--_cpu-fallback" in sys.argv
BASELINE_EDGES_PER_SEC = 2_000_000.0

# a healthy tunnel initializes in ~2s; a broken one hangs forever (the
# whole round-4 window measured exactly these two modes). Keep the
# worst-case probe budget well under any plausible external timeout so
# the CPU fallback still emits its lines: 2 x 150s + 5s ≈ 5 min.
PROBE_TIMEOUT_S = float(os.environ.get("EULER_BENCH_PROBE_TIMEOUT", 150.0))
PROBE_ATTEMPTS = int(os.environ.get("EULER_BENCH_PROBE_ATTEMPTS", 2))
PROBE_SLEEP_S = (5.0, 0.0)
# internal wall-clock budget for the remote leg (VERDICT r3 #1): the remote
# leg must never be the reason the artifact is empty. A watchdog thread
# force-emits partial results and exits the process if this expires —
# os._exit works even when the main thread is stuck in a blocked C call.
REMOTE_BUDGET_S = float(os.environ.get("EULER_BENCH_REMOTE_BUDGET", 420.0))

# server processes spawned by the remote leg, killable from the watchdog
_REMOTE_PROCS: list = []

# backend-probe failure metadata (timeouts, rc/stderr tails): attached to
# the emitted JSON so a CPU-fallback run is self-explaining from the
# artifact alone, not only from interleaved stderr. Survives the CPU
# re-exec via EULER_BENCH_PROBE_META.
_PROBE_FAILURES: list = []

# probe-outcome cache: on an accelerator-less box every bench run used to
# burn 2 × 150 s probe timeouts before falling back to CPU (BENCH_r05
# tail). A cached NEGATIVE probe (boot-keyed + TTL'd, so a reboot or a
# fixed tunnel invalidates it) skips straight to the CPU re-exec.
# EULER_BENCH_PROBE_CACHE=0 opts out; a positive probe is cached too,
# purely as a record (positives never skip the live probe — a tunnel
# that died since must still be detected).
PROBE_CACHE_PATH = os.path.join(
    tempfile.gettempdir(), "euler_bench_probe_cache.json"
)
PROBE_CACHE_TTL_S = float(
    os.environ.get("EULER_BENCH_PROBE_TTL", 6 * 3600.0)
)


def _probe_cache_enabled() -> bool:
    return os.environ.get("EULER_BENCH_PROBE_CACHE", "1") != "0"


def _boot_key() -> str:
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        return ""


def _read_probe_cache() -> dict | None:
    if not _probe_cache_enabled():
        return None
    try:
        with open(PROBE_CACHE_PATH) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if rec.get("boot_key") != _boot_key():
        return None
    if time.time() - float(rec.get("ts", 0)) > PROBE_CACHE_TTL_S:
        return None
    return rec


def _write_probe_cache(ok: bool) -> None:
    if not _probe_cache_enabled():
        return
    tmp = f"{PROBE_CACHE_PATH}.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(
                {
                    "ok": bool(ok),
                    "boot_key": _boot_key(),
                    "ts": time.time(),
                    "failures": list(_PROBE_FAILURES),
                },
                f,
            )
            # fsync before the atomic publish: without it the rename can
            # land while the bytes are still page-cache-only, and a crash
            # leaves an EMPTY committed file (reads tolerate the torn
            # JSON, but then the whole probe burn repeats)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, PROBE_CACHE_PATH)
    except OSError:
        pass


def _probe_meta() -> dict | None:
    env_meta = os.environ.get("EULER_BENCH_PROBE_META")
    if env_meta:
        try:
            return json.loads(env_meta)
        except ValueError:
            return {"raw": env_meta[:300]}
    if _PROBE_FAILURES:
        return {
            "attempts": PROBE_ATTEMPTS,
            "timeout_s": PROBE_TIMEOUT_S,
            "failures": list(_PROBE_FAILURES),
        }
    return None


def emit(
    value: float,
    extra: dict | None = None,
    metric: str = "graphsage_sampled_edges_per_sec_per_chip",
    unit: str = "edges/s",
    baseline: float | None = BASELINE_EDGES_PER_SEC,
) -> None:
    rec = {
        "metric": metric,
        "value": round(float(value), 1),
        "unit": unit,
    }
    if baseline:
        rec["vs_baseline"] = round(float(value) / baseline, 4)
    if extra:
        rec.update(extra)
    print(json.dumps(rec))
    sys.stdout.flush()


def _reexec_cpu(probe_meta: dict) -> None:
    """Replace this process with a CPU-pinned copy of itself.

    A fresh process = fresh jax backend state; the env var beats any
    in-process config mutation after a failed/hung init. The probe
    metadata rides along so the fallback's JSON artifact explains WHY it
    ran on CPU."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["EULER_BENCH_PROBE_META"] = json.dumps(probe_meta)
    # drop the axon pool hint so sitecustomize skips the tunnel
    # registration entirely in the fresh process
    env.pop("PALLAS_AXON_POOL_IPS", None)
    os.execve(
        sys.executable,
        [sys.executable, os.path.abspath(__file__), *sys.argv[1:],
         "--_cpu-fallback"],
        env,
    )


def warm_backend() -> str:
    """Bring up the JAX backend safely; return the platform name.

    Probes `jax.devices()` in a subprocess first (bounded wall clock even if
    init hangs), retrying a few times; on exhaustion re-execs this script
    with JAX_PLATFORMS=cpu so a broken accelerator tunnel still yields a
    benchmark number instead of an empty round.
    """
    if SMOKE or CPU_FALLBACK:
        # the axon sitecustomize pins jax_platforms="axon,cpu" at interpreter
        # start; env vars are already read, so only a config update works
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        cached = _read_probe_cache()
        if cached is not None and not cached.get("ok", False):
            # this boot already proved the accelerator unreachable:
            # skip the 2 × 150 s probe burn and go straight to CPU
            print(
                "# cached negative accelerator probe"
                f" ({PROBE_CACHE_PATH}); skipping probes"
                " (EULER_BENCH_PROBE_CACHE=0 to re-probe)",
                file=sys.stderr,
            )
            _reexec_cpu({
                "cached": True,
                "cache_ts": cached.get("ts"),
                "failures": cached.get("failures", []),
            })
        probe = "import jax; print(jax.devices()[0].platform)"
        ok = False
        for attempt in range(PROBE_ATTEMPTS):
            t0 = time.time()
            try:
                r = subprocess.run(
                    [sys.executable, "-c", probe],
                    capture_output=True,
                    text=True,
                    timeout=PROBE_TIMEOUT_S,
                )
                if r.returncode == 0:
                    print(
                        f"# backend probe ok ({r.stdout.strip().splitlines()[-1]},"
                        f" {time.time() - t0:.0f}s)",
                        file=sys.stderr,
                    )
                    ok = True
                    break
                tail = (
                    r.stderr.strip().splitlines()[-1][:200]
                    if r.stderr.strip()
                    else "<no stderr>"
                )
                _PROBE_FAILURES.append(
                    {"attempt": attempt + 1, "rc": r.returncode,
                     "stderr_tail": tail, "elapsed_s": round(time.time() - t0, 1)}
                )
                print(
                    f"# backend probe attempt {attempt + 1}"
                    f" rc={r.returncode}: {tail}",
                    file=sys.stderr,
                )
            except subprocess.TimeoutExpired:
                _PROBE_FAILURES.append(
                    {"attempt": attempt + 1, "timeout": True,
                     "timeout_s": PROBE_TIMEOUT_S}
                )
                print(
                    f"# backend probe attempt {attempt + 1} timed out"
                    f" after {PROBE_TIMEOUT_S:.0f}s",
                    file=sys.stderr,
                )
            time.sleep(PROBE_SLEEP_S[min(attempt, len(PROBE_SLEEP_S) - 1)])
        _write_probe_cache(ok)
        if not ok:
            print("# accelerator unavailable; re-exec on CPU", file=sys.stderr)
            _reexec_cpu({
                "attempts": PROBE_ATTEMPTS,
                "timeout_s": PROBE_TIMEOUT_S,
                "failures": _PROBE_FAILURES,
            })

    # main-thread first touch: everything after this (incl. prefetch worker
    # threads calling device_put) sees an initialized backend
    import jax

    devs = jax.devices()
    import jax.numpy as jnp

    jnp.zeros((8, 8)).block_until_ready()
    return devs[0].platform


def _measure_training(
    batch_fn,
    cache,
    dims,
    batch_size,
    fanouts,
    warmup,
    steps,
    steps_per_call,
    bf16,
    model_dir,
):
    """Shared GraphSAGE measurement harness for both bench legs: pallas
    auto, optional bf16 convs, prefetched K-step scan dispatch, timed
    steady-state window. Returns (edges_per_sec, edges_per_step)."""
    import jax

    from euler_tpu.estimator import Estimator, EstimatorConfig
    from euler_tpu.estimator.estimator import stack_batches
    from euler_tpu.estimator.prefetch import Prefetcher
    from euler_tpu.models import GraphSAGESupervised

    if "EULER_TPU_PALLAS" not in os.environ:
        from euler_tpu.ops import set_pallas

        set_pallas("auto")
    conv_kwargs = None
    if bf16:
        import jax.numpy as jnp

        conv_kwargs = {"dtype": jnp.bfloat16}
    model = GraphSAGESupervised(dims=dims, label_dim=2, conv_kwargs=conv_kwargs)
    if getattr(batch_fn, "is_device_flow", False):
        # on-device sampling: batches are traced inside the scanned train
        # step from PRNG keys — no host sampling, no prefetch, no wire
        prefetch = batch_fn
    else:
        # workers stage K-step stacked batches onto the device so H2D and
        # host sampling overlap the scanned device steps
        prefetch = Prefetcher(
            stack_batches(batch_fn, steps_per_call),
            depth=4,
            workers=4,
            device_put=True,
        )
    try:
        est = Estimator(
            model,
            prefetch,
            EstimatorConfig(
                model_dir=model_dir,
                learning_rate=0.01,
                log_steps=10**9,
                steps_per_call=steps_per_call,
            ),
            feature_cache=cache,
        )
        # edges sampled per step: every hop's sample_neighbor draws
        edges_per_step = 0
        width = batch_size
        for k in fanouts:
            edges_per_step += width * k
            width *= k
        est.train(total_steps=warmup, log=False, save=False)  # compile+warm
        t0 = time.perf_counter()
        est.train(total_steps=steps, log=False, save=False)
        jax.block_until_ready(est.params)
        dt = time.perf_counter() - t0
    finally:
        if hasattr(prefetch, "close"):
            prefetch.close()
    return steps * edges_per_step / dt, edges_per_step


def _skewed_weighted_graph(num_nodes: int, seed: int):
    """Power-law-ish weighted digraph, arrays built directly: most nodes
    keep a small out-degree, a hub tier fans ~10× wider — the degree
    regime the paged device lane exists for (dense pays the hub width on
    EVERY row's draw scan; paged pays ⌈deg/P⌉ pages only on hub rows)."""
    from euler_tpu.datasets.synthetic import synthetic_meta
    from euler_tpu.graph.store import Graph, GraphStore

    rng = np.random.default_rng(seed)
    n = int(num_nodes)
    deg = rng.integers(8, 16, n)
    hubs = rng.choice(n, max(n // 100, 1), replace=False)
    deg[hubs] = rng.integers(96, 160, len(hubs))
    ids = np.arange(1, n + 1, dtype=np.uint64)
    e = int(deg.sum())
    dst = rng.integers(1, n + 1, size=e).astype(np.uint64)
    ew = rng.uniform(0.5, 2.0, size=e).astype(np.float32)
    feat_dim, label_dim = 16, 2
    meta = synthetic_meta(feat_dim, label_dim, 1)
    arrays = {
        "node_ids": ids,
        "node_types": np.zeros(n, dtype=np.int32),
        "node_weights": np.ones(n, dtype=np.float32),
        "edge_src": np.repeat(ids, deg),
        "edge_dst": dst,
        "edge_types": np.zeros(e, dtype=np.int32),
        "edge_weights": ew,
        "adj_0_indptr": np.r_[0, np.cumsum(deg)].astype(np.int64),
        "adj_0_dst": dst,
        "adj_0_w": ew,
        "adj_0_eidx": np.arange(e, dtype=np.int64),
        "nf_dense_0": rng.normal(0.0, 1.0, (n, feat_dim)).astype(np.float32),
        "nf_dense_1": np.zeros((n, label_dim), np.float32),
        "glabel_indptr": np.zeros(1, dtype=np.int64),
        "glabel_nodes": np.zeros(0, dtype=np.uint64),
    }
    meta.node_weight_sums.append([float(n)])
    meta.edge_weight_sums.append([float(ew.sum())])
    return Graph(meta, [GraphStore(meta, arrays, part=0)])


def _paged_device_ab(smoke: bool) -> dict:
    """Paged vs dense device-lane sampling A/B on a skewed weighted
    graph (EULER_BENCH_PAGED=0 skips). Measures pure traced-sampling
    throughput — the quantity the layouts differ on — plus the standing
    bit-identity oracle (paged and dense draw the same batch from the
    same key) and one interpret-mode Pallas-kernel validation at micro
    size, so the artifact records that the kernel entry points and the
    jnp reference agree on this very build."""
    import jax

    from euler_tpu.dataflow import DeviceSageFlow

    n, batch, fanouts, reps = (
        (4_000, 64, [5, 5], 10) if smoke else (50_000, 512, [10, 10], 30)
    )
    g = _skewed_weighted_graph(n, seed=13)
    flows = {
        lay: DeviceSageFlow(
            g, fanouts=fanouts, batch_size=batch, layout=lay,
            max_degree=4096,
        )
        for lay in ("dense", "paged")
    }
    edges_per_step = 0
    width = batch
    for k in fanouts:
        edges_per_step += width * k
        width *= k
    # the A/B oracle the parity tests pin, re-checked in the artifact
    leaves = {
        lay: jax.tree_util.tree_leaves(
            jax.jit(f.sample)(jax.random.PRNGKey(0))
        )
        for lay, f in flows.items()
    }
    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves["dense"], leaves["paged"])
    )

    def measure(flow) -> float:
        fn = jax.jit(flow.sample)
        jax.block_until_ready(
            jax.tree_util.tree_leaves(fn(jax.random.PRNGKey(1)))
        )
        t0 = time.perf_counter()
        out = None
        for t in range(reps):
            out = fn(jax.random.PRNGKey(100 + t))
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        return reps * edges_per_step / (time.perf_counter() - t0)

    # interleaved best-of-2 so one GC pause can't decide the ratio
    dense_eps = max(measure(flows["dense"]), measure(flows["dense"]))
    paged_eps = max(measure(flows["paged"]), measure(flows["paged"]))

    # interpret-mode kernel validation at micro size (pallas interpret
    # emulates each DMA in Python — keep the draw count tiny)
    from euler_tpu.ops import pallas_mode, set_pallas

    micro = DeviceSageFlow(
        g, fanouts=[2], batch_size=8, layout="paged", max_degree=4096
    )
    ref = jax.jit(micro.sample)(jax.random.PRNGKey(3))
    prev = pallas_mode()
    set_pallas("interpret")
    try:
        ker = micro.sample(jax.random.PRNGKey(3))
    finally:
        set_pallas(prev)
    interp_ok = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(ker)
        )
    )
    return {
        "paged": True,
        "paged_sample_edges_per_sec": round(paged_eps, 1),
        "dense_sample_edges_per_sec": round(dense_eps, 1),
        "paged_over_dense": round(paged_eps / max(dense_eps, 1e-9), 3),
        "paged_bit_identical": bool(identical),
        "paged_interpret_ok": bool(interp_ok),
        "paged_hub_degree": int(flows["paged"].max_deg),
        "page_size": int(flows["paged"].page_size),
    }


def _mutation_lane(smoke: bool) -> dict:
    """Streaming-mutation lane (ISSUE 8; EULER_BENCH_MUTATION=0 opt-out):
    sustained writer upserts/s into the per-shard delta buffers, publish
    latency at two delta sizes, post-publish read recovery (the first
    read pays the merged store's lazy sampler/index rebuilds), and the
    standing merged == from-scratch bit-parity oracle — reads stay
    epoch-consistent while the writer streams, and every published
    epoch equals a cold build of the mutated graph."""
    from euler_tpu.distributed.writer import GraphWriter
    from euler_tpu.graph import Graph
    from euler_tpu.graph.builder import build_from_json

    n, stream_small, stream_large = (
        (400, 400, 2000) if smoke else (5000, 5000, 25000)
    )
    rng = np.random.default_rng(11)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=8).tolist()}]}
        for i in range(n)
    ]
    # unique (src, dst, type) keys by construction: upsert semantics
    # target ONE edge per key, so the from-scratch replay must too
    edges = [
        {"src": s, "dst": (s + off) % n + 1, "type": 0,
         "weight": float(rng.integers(1, 5)), "features": []}
        for s in range(1, n + 1)
        for off in (1, 3, 7)
    ]
    data = {"nodes": nodes, "edges": edges}
    g = Graph.from_json(data, num_partitions=2)
    read_ids = np.arange(1, min(n, 256) + 1, dtype=np.uint64)

    def read_rate(reps: int = 10) -> float:
        t0 = time.perf_counter()
        for k in range(reps):
            g.get_dense_feature(read_ids, ["feat"])
            g.sample_neighbor(
                read_ids, None, 5, rng=np.random.default_rng(k)
            )
        return reps / (time.perf_counter() - t0)

    pre_rate = read_rate()

    def mk_stream(k: int, seed: int):
        r = np.random.default_rng(seed)
        return (
            r.integers(1, n + 1, size=k).astype(np.uint64),
            r.integers(1, n + 1, size=k).astype(np.uint64),
            r.integers(1, 9, size=k).astype(np.float32),
        )

    writer = GraphWriter(g, batch_rows=1024)
    streams = [mk_stream(stream_large, 21), mk_stream(stream_small, 22)]
    # sustained staging throughput: client batching + scatter + per-shard
    # delta appends, publish excluded
    src, dst, w = streams[0]
    t0 = time.perf_counter()
    for lo in range(0, stream_large, 1024):
        writer.upsert_edges(
            src[lo : lo + 1024], dst[lo : lo + 1024], None,
            w[lo : lo + 1024],
        )
    writer.flush()
    stage_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    writer.publish()
    publish_large_ms = (time.perf_counter() - t0) * 1e3
    src, dst, w = streams[1]
    writer.upsert_edges(src, dst, None, w)
    writer.flush()
    t0 = time.perf_counter()
    writer.publish()
    publish_small_ms = (time.perf_counter() - t0) * 1e3
    # post-publish read recovery: the first read batch pays the merged
    # store's lazy rebuilds (edge-key index, samplers), then steady state
    t0 = time.perf_counter()
    g.get_dense_feature(read_ids, ["feat"])
    g.sample_neighbor(read_ids, None, 5, rng=np.random.default_rng(0))
    recovery_ms = (time.perf_counter() - t0) * 1e3
    post_rate = read_rate()
    # bit parity: replay the same streams onto the JSON, rebuild cold
    ref_edges = [dict(e) for e in edges]
    index = {(e["src"], e["dst"], e["type"]): e for e in ref_edges}
    for src, dst, w in streams:
        for s_, d_, w_ in zip(src, dst, w):
            key = (int(s_), int(d_), 0)
            rec = index.get(key)
            if rec is None:
                rec = {"src": key[0], "dst": key[1], "type": 0,
                       "weight": float(w_), "features": []}
                ref_edges.append(rec)
                index[key] = rec
            else:
                rec["weight"] = float(w_)
    _, ref_shards = build_from_json(
        {"nodes": nodes, "edges": ref_edges}, 2
    )
    parity = all(
        np.array_equal(
            np.asarray(g.shards[p].arrays[k]), np.asarray(ref_shards[p][k])
        )
        for p in range(2)
        for k in ref_shards[p]
    )
    return {
        "mutation": True,
        "mutation_upserts_per_sec": round(stream_large / stage_s, 1),
        "mutation_publish_ms_small": round(publish_small_ms, 2),
        "mutation_publish_ms_large": round(publish_large_ms, 2),
        "mutation_publish_rows_small": int(stream_small),
        "mutation_publish_rows_large": int(stream_large),
        "mutation_read_recovery_ms": round(recovery_ms, 2),
        "mutation_read_rate_post_over_pre": round(
            post_rate / max(pre_rate, 1e-9), 3
        ),
        "mutation_bit_parity": bool(parity),
    }


def _durability_lane(smoke: bool) -> dict:
    """Durability lane (ISSUE 9; EULER_BENCH_DURABILITY=0 opt-out):
    acked-writes/s through the full stage+WAL path with fsync on vs off
    (the fsync-cadence vs write-throughput tradeoff SCALE.md documents),
    snapshot cost at the publish cadence, crash→recovered-first-read
    latency, and the recovered == pre-crash bit-parity oracle."""
    import shutil
    import tempfile

    from euler_tpu.distributed.service import GraphService
    from euler_tpu.graph import Graph
    from euler_tpu.graph import wal as walmod
    from euler_tpu.graph.store import GraphStore

    n, batches, rows_per = (50, 40, 64) if smoke else (2000, 200, 256)
    rng = np.random.default_rng(17)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=8).tolist()}]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": s % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for s in range(1, n + 1)
    ]
    data = {"nodes": nodes, "edges": edges}
    tmp = tempfile.mkdtemp(prefix="etpu_bench_wal_")
    old_fsync = os.environ.get("EULER_TPU_WAL_FSYNC")
    try:

        def acked_writes_per_sec(mode: str) -> tuple[float, GraphService]:
            os.environ["EULER_TPU_WAL_FSYNC"] = mode
            g = Graph.from_json(data, num_partitions=1)
            svc = GraphService(
                g.shards[0], g.meta, 0,
                wal_dir=os.path.join(tmp, f"wal_{mode}"),
            )
            r = np.random.default_rng(5)
            reqs = []
            for b in range(batches):
                src = r.integers(1, n + 1, rows_per).astype(np.uint64)
                dst = r.integers(1, n + 1, rows_per).astype(np.uint64)
                reqs.append([
                    f"bench:{mode}:{b}", src, dst,
                    np.zeros(rows_per, np.int32),
                    r.random(rows_per).astype(np.float32),
                    np.empty(0, np.uint64), np.empty(0, np.uint64),
                    np.empty(0, np.int32), np.empty(0, np.float32),
                ])
            t0 = time.perf_counter()
            for a in reqs:
                svc.dispatch("upsert_edges", a)  # staged + logged + synced
            dt = time.perf_counter() - t0
            return batches * rows_per / dt, svc

        fsync_rate, svc = acked_writes_per_sec("batch")
        nofsync_rate, svc_off = acked_writes_per_sec("off")
        svc_off.stop()

        # snapshot cost at the cadence point: publish, then serialize the
        # published store + applied window and trim the WAL
        svc.dispatch("publish_epoch", ["bench:pub"])
        t0 = time.perf_counter()
        assert svc.snapshot_now()
        snapshot_ms = (time.perf_counter() - t0) * 1e3
        # a post-snapshot acked suffix, so recovery replays WAL too
        svc.dispatch("upsert_edges", [
            "bench:suffix",
            np.asarray([1], np.uint64), np.asarray([2], np.uint64),
            np.zeros(1, np.int32), np.asarray([2.0], np.float32),
            np.empty(0, np.uint64), np.empty(0, np.uint64),
            np.empty(0, np.int32), np.empty(0, np.float32),
        ])
        live = {
            k: np.array(v) for k, v in svc.store.arrays.items()
        }
        # crash: no graceful stop — recovery gets only what hit the disk
        svc.server.shutdown()
        svc.server.server_close()
        g2 = Graph.from_json(data, num_partitions=1)
        t0 = time.perf_counter()
        rec = walmod.recover(
            g2.meta, 0, os.path.join(tmp, "wal_batch"), g2.shards[0]
        )
        rec.store.get_dense_feature(
            np.arange(1, min(n, 64) + 1, dtype=np.uint64), ["feat"]
        )
        recovery_ms = (time.perf_counter() - t0) * 1e3
        parity = set(live) == set(rec.store.arrays) and all(
            np.array_equal(np.asarray(rec.store.arrays[k]), live[k])
            for k in live
        )
        return {
            "durability": True,
            "durability_acked_writes_per_sec_fsync": round(fsync_rate, 1),
            "durability_acked_writes_per_sec_nofsync": round(
                nofsync_rate, 1
            ),
            "durability_fsync_overhead_x": round(
                nofsync_rate / max(fsync_rate, 1e-9), 3
            ),
            "durability_snapshot_ms": round(snapshot_ms, 2),
            "durability_recovery_ms": round(recovery_ms, 2),
            "durability_recovered_bit_parity": bool(parity),
        }
    finally:
        if old_fsync is None:
            os.environ.pop("EULER_TPU_WAL_FSYNC", None)
        else:
            os.environ["EULER_TPU_WAL_FSYNC"] = old_fsync
        shutil.rmtree(tmp, ignore_errors=True)


def _availability_lane(smoke: bool) -> dict:
    """Availability lane (ISSUE 13; EULER_BENCH_AVAILABILITY=0 opt-out):
    replica-group cost/benefit on the artifact — acked-rows/s under
    quorum vs async vs solo acks (what a follower ack on the commit path
    costs), the write-unavailability window from a primary kill to the
    first accepted post-failover write (lease-bounded), follower
    catch-up MB/s over `wal_ship`, and the caught-up follower ==
    primary bit-parity oracle."""
    import shutil
    import tempfile

    from euler_tpu.distributed.registry import Registry
    from euler_tpu.distributed.service import GraphService
    from euler_tpu.graph import Graph

    n, batches, rows_per = (50, 30, 64) if smoke else (1000, 150, 256)
    ttl = 1.0
    rng = np.random.default_rng(23)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=8).tolist()}]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": s % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for s in range(1, n + 1)
    ]
    data = {"nodes": nodes, "edges": edges}
    tmp = tempfile.mkdtemp(prefix="etpu_bench_avail_")
    old_ack = os.environ.get("EULER_TPU_REPL_ACK")

    def reqs(tag):
        r = np.random.default_rng(5)
        out = []
        for b in range(batches):
            src = r.integers(1, n + 1, rows_per).astype(np.uint64)
            dst = r.integers(1, n + 1, rows_per).astype(np.uint64)
            out.append([
                f"avail:{tag}:{b}", src, dst,
                np.zeros(rows_per, np.int32),
                r.random(rows_per).astype(np.float32),
                np.empty(0, np.uint64), np.empty(0, np.uint64),
                np.empty(0, np.int32), np.empty(0, np.float32),
            ])
        return out

    def acked_rows_per_sec(svc, tag):
        rs = reqs(tag)
        t0 = time.perf_counter()
        for a in rs:
            svc.dispatch("upsert_edges", a)
        return batches * rows_per / (time.perf_counter() - t0)

    def boot_member(sub, rid, mode, group_size=2):
        os.environ["EULER_TPU_REPL_ACK"] = mode
        g = Graph.from_json(data, num_partitions=1)
        return GraphService(
            g.shards[0], g.meta, 0,
            registry=Registry(os.path.join(tmp, sub, "reg"), ttl=2.0),
            wal_dir=os.path.join(tmp, sub, f"wal_r{rid}"),
            replica=rid, group_size=group_size, lease_ttl=ttl,
        ).start()

    def wait_role(svc, role, timeout_s=20.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if svc.repl_status()["role"] == role:
                return
            time.sleep(0.02)
        raise TimeoutError(f"replica never became {role}")

    def hard_kill(svc):
        svc._repl._stop.set()
        svc.server.shutdown()
        svc.server.server_close()
        if svc._beat is not None:
            svc._beat.set()

    svcs = []
    try:
        # solo baseline: same batches, no replica group on the ack path
        solo = GraphService(
            Graph.from_json(data, num_partitions=1).shards[0],
            Graph.from_json(data, num_partitions=1).meta, 0,
            wal_dir=os.path.join(tmp, "solo_wal"),
        )
        svcs.append(solo)
        solo_rate = acked_rows_per_sec(solo, "solo")

        # async group: the primary writes alone first (follower joins
        # late), so the same run also times follower catch-up
        pri_a = boot_member("a", 0, "async")
        svcs.append(pri_a)
        wait_role(pri_a, "primary")
        async_rate = acked_rows_per_sec(pri_a, "async")
        shipped_bytes = pri_a._wal.tell()
        t0 = time.perf_counter()
        fol_a = boot_member("a", 1, "async")
        svcs.append(fol_a)
        deadline = time.monotonic() + 60
        while fol_a._wal.tell() < shipped_bytes:
            if time.monotonic() > deadline:
                raise TimeoutError("follower catch-up stalled")
            time.sleep(0.005)
        catchup_s = time.perf_counter() - t0
        parity = set(pri_a.store.arrays) == set(fol_a.store.arrays) and all(
            np.array_equal(
                np.asarray(fol_a.store.arrays[k]),
                np.asarray(pri_a.store.arrays[k]),
            )
            for k in pri_a.store.arrays
        )

        # quorum group: every ack waits for the follower's durable ship
        pri_q = boot_member("q", 0, "quorum")
        fol_q = boot_member("q", 1, "quorum")
        svcs += [pri_q, fol_q]
        wait_role(pri_q, "primary")
        pri_q.dispatch("upsert_edges", reqs("warm")[0])  # follower attach
        quorum_rate = acked_rows_per_sec(pri_q, "quorum")

        # unavailability window: kill the primary, poll the survivor
        # with ONE idempotency-keyed row until the promotion accepts it
        hard_kill(pri_q)
        fol_q._repl.ack_mode = "async"  # sole survivor: no quorum left
        probe = reqs("failover")[0]
        t0 = time.perf_counter()
        deadline = time.monotonic() + 60
        while True:
            try:
                fol_q.dispatch("upsert_edges", probe)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.005)
        window_ms = (time.perf_counter() - t0) * 1e3
        return {
            "availability": True,
            "availability_bit_parity": bool(parity),
            "availability_unavail_window_ms": round(window_ms, 1),
            "availability_quorum_rows_per_sec": round(quorum_rate, 1),
            "availability_async_rows_per_sec": round(async_rate, 1),
            "availability_solo_rows_per_sec": round(solo_rate, 1),
            "availability_quorum_overhead_x": round(
                solo_rate / max(quorum_rate, 1e-9), 3
            ),
            "availability_catchup_mb_per_sec": round(
                shipped_bytes / 1e6 / max(catchup_s, 1e-9), 2
            ),
        }
    finally:
        if old_ack is None:
            os.environ.pop("EULER_TPU_REPL_ACK", None)
        else:
            os.environ["EULER_TPU_REPL_ACK"] = old_ack
        for svc in svcs:
            try:
                svc.stop()
            except OSError:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _bytes_lane(smoke: bool) -> dict:
    """Byte-path lane (ISSUE 16; EULER_BENCH_BYTES=0 opt-out): what the
    compact encodings actually save on the artifact, A/B'd in one run —
    dense wire bytes/batch f32 vs bf16 vs int8 (real client wire
    counters), warm-cache resident bytes per dtype, neighbor planes raw
    vs delta+varint, and replication catch-up MB/s / quorum acked-rows
    overhead with the identity codec + lockstep shipping vs the default
    compressed + pipelined path."""
    import shutil
    import tempfile

    from euler_tpu.distributed.client import RemoteShard
    from euler_tpu.distributed.registry import Registry
    from euler_tpu.distributed.service import GraphService
    from euler_tpu.graph import Graph

    n, dim, ids_per, batches, rows_per = (
        (64, 32, 48, 60, 64) if smoke else (2000, 64, 256, 150, 256)
    )
    # small ship batches force a multi-batch catch-up stream even at
    # smoke sizing — that is the regime the pipelined path exists for
    ship_max = 32768 if smoke else 262144
    ttl = 1.0
    rng = np.random.default_rng(16)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=dim).tolist()}]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": s % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for s in range(1, n + 1)
    ]
    data = {"nodes": nodes, "edges": edges}
    tmp = tempfile.mkdtemp(prefix="etpu_bench_bytes_")
    knobs = (
        "EULER_TPU_PAGE_DTYPE", "EULER_TPU_WIRE_CODEC",
        "EULER_TPU_SHIP_PIPELINE", "EULER_TPU_REPL_ACK",
        "EULER_TPU_SHIP_MAX_BYTES",
    )
    saved = {k: os.environ.get(k) for k in knobs}
    svcs = []

    def reqs(tag):
        r = np.random.default_rng(7)
        out = []
        for b in range(batches):
            src = r.integers(1, n + 1, rows_per).astype(np.uint64)
            dst = r.integers(1, n + 1, rows_per).astype(np.uint64)
            out.append([
                f"bytes:{tag}:{b}", src, dst,
                np.zeros(rows_per, np.int32),
                r.random(rows_per).astype(np.float32),
                np.empty(0, np.uint64), np.empty(0, np.uint64),
                np.empty(0, np.int32), np.empty(0, np.float32),
            ])
        return out

    def acked_rows_per_sec(svc, tag):
        rs = reqs(tag)
        t0 = time.perf_counter()
        for a in rs:
            svc.dispatch("upsert_edges", a)
        return batches * rows_per / (time.perf_counter() - t0)

    def boot_member(sub, rid, mode, group_size=2):
        os.environ["EULER_TPU_REPL_ACK"] = mode
        g = Graph.from_json(data, num_partitions=1)
        return GraphService(
            g.shards[0], g.meta, 0,
            registry=Registry(os.path.join(tmp, sub, "reg"), ttl=2.0),
            wal_dir=os.path.join(tmp, sub, f"wal_r{rid}"),
            replica=rid, group_size=group_size, lease_ttl=ttl,
        ).start()

    def wait_role(svc, role, timeout_s=20.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if svc.repl_status()["role"] == role:
                return
            time.sleep(0.02)
        raise TimeoutError(f"replica never became {role}")

    try:
        # -- dense wire + warm-cache A/B: one server, fresh client per
        # page dtype so the sticky negotiation flag and cache reset
        g = Graph.from_json(data, num_partitions=1)
        read_svc = GraphService(g.shards[0], g.meta, 0).start()
        svcs.append(read_svc)
        ids = np.arange(1, ids_per + 1, dtype=np.uint64)

        def dense_leg(kind):
            os.environ["EULER_TPU_PAGE_DTYPE"] = kind
            rs = RemoteShard(0, [(read_svc.host, read_svc.port)])
            try:
                a = rs.get_dense_feature(ids, ["feat"])
                wire = int(rs.wire_bytes_in.get("get_dense_feature", 0))
                rs.get_dense_feature(ids, ["feat"])  # warm: cache hit
                rewire = (
                    int(rs.wire_bytes_in.get("get_dense_feature", 0))
                    - wire
                )
                resident = rs._cache.nbytes if rs._cache else 0
            finally:
                rs.close()
            return np.asarray(a), wire, resident, rewire

        f32_vals, f32_wire, f32_res, f32_rewire = dense_leg("f32")
        bf_vals, bf_wire, bf_res, _ = dense_leg("bf16")
        _, i8_wire, _, _ = dense_leg("int8")
        os.environ.pop("EULER_TPU_PAGE_DTYPE", None)
        bf_err = float(np.max(np.abs(bf_vals - f32_vals)))

        # neighbor planes: identity codec (raw u64 wire) vs the default
        # delta+varint offer — exact either way, bytes differ
        def nb_leg(codec_name):
            os.environ["EULER_TPU_WIRE_CODEC"] = codec_name
            rs = RemoteShard(0, [(read_svc.host, read_svc.port)])
            try:
                rs.get_full_neighbor(ids, [0])
                return int(rs.wire_bytes_in.get("get_full_neighbor", 0))
            finally:
                rs.close()

        nb_raw = nb_leg("id")
        nb_delta = nb_leg("zlib")

        # -- replication A/B: identity + lockstep vs zlib + pipelined.
        # Each leg measures quorum acked-rows/s (vs one solo baseline)
        # and follower catch-up MB/s with a late-joining follower.
        solo = GraphService(
            Graph.from_json(data, num_partitions=1).shards[0],
            Graph.from_json(data, num_partitions=1).meta, 0,
            wal_dir=os.path.join(tmp, "solo_wal"),
        )
        svcs.append(solo)
        solo_rate = acked_rows_per_sec(solo, "solo")

        def finished(members):
            for svc in members:
                svcs.remove(svc)
                try:
                    svc.stop()
                except OSError:
                    pass

        def catchup_once(sub):
            # async primary writes a backlog alone (2x the quorum
            # traffic so shipping dominates follower boot cost), then
            # the follower joins late and streams it
            pri_a = boot_member(sub, 0, "async")
            svcs.append(pri_a)
            wait_role(pri_a, "primary")
            for tag in (f"w1{sub}", f"w2{sub}", f"w3{sub}", f"w4{sub}"):
                acked_rows_per_sec(pri_a, tag)
            shipped = pri_a._wal.tell()
            t0 = time.perf_counter()
            fol_a = boot_member(sub, 1, "async")
            svcs.append(fol_a)
            deadline = time.monotonic() + 60
            while fol_a._wal.tell() < shipped:
                if time.monotonic() > deadline:
                    raise TimeoutError("follower catch-up stalled")
                time.sleep(0.0005)  # fine: the whole stream is ~50ms
            mbps = shipped / 1e6 / max(time.perf_counter() - t0, 1e-9)
            st = fol_a.repl_status()
            finished([pri_a, fol_a])
            return mbps, st

        def quorum_once(sub):
            pri_q = boot_member(sub, 0, "quorum")
            fol_q = boot_member(sub, 1, "quorum")
            svcs.extend([pri_q, fol_q])
            wait_role(pri_q, "primary")
            pri_q.dispatch("upsert_edges", reqs(f"warm{sub}")[0])
            rate = acked_rows_per_sec(pri_q, sub)
            finished([pri_q, fol_q])
            return rate

        def ship_leg(sub, codec_name, pipeline):
            # best-of-N: single-run numbers at smoke sizing are noisy
            # (fsync and scheduler variance swamp a ~50ms stream)
            os.environ["EULER_TPU_WIRE_CODEC"] = codec_name
            os.environ["EULER_TPU_SHIP_PIPELINE"] = pipeline
            os.environ["EULER_TPU_SHIP_MAX_BYTES"] = str(ship_max)
            q_rate = max(quorum_once(f"q{sub}{i}") for i in range(3))
            mbps, st = max(
                (catchup_once(f"a{sub}{i}") for i in range(4)),
                key=lambda r: r[0],
            )
            return q_rate, mbps, st

        id_rate, id_mbps, _ = ship_leg("id", "id", "0")
        zl_rate, zl_mbps, zl_st = ship_leg("zl", "zlib", "1")
        wire_ratio = zl_st["ship_bytes"] / max(
            zl_st["ship_wire_bytes"], 1
        )
        return {
            "bytes": True,
            "bytes_dense_f32_per_batch": int(f32_wire),
            "bytes_dense_bf16_per_batch": int(bf_wire),
            "bytes_dense_int8_per_batch": int(i8_wire),
            "bytes_dense_reduction_pct": round(
                100.0 * (1 - bf_wire / max(f32_wire, 1)), 1
            ),
            "bytes_dense_bf16_max_err": round(bf_err, 6),
            "bytes_warm_cache_f32": int(f32_res),
            "bytes_warm_cache_bf16": int(bf_res),
            "bytes_warm_cache_saved_pct": round(
                100.0 * (1 - bf_res / max(f32_res, 1)), 1
            ),
            "bytes_warm_rewire": int(f32_rewire),  # 0 == cache held
            "bytes_full_nb_raw": int(nb_raw),
            "bytes_full_nb_delta": int(nb_delta),
            "bytes_catchup_mb_per_sec_id": round(id_mbps, 2),
            "bytes_catchup_mb_per_sec_zlib": round(zl_mbps, 2),
            "bytes_quorum_overhead_x_id": round(
                solo_rate / max(id_rate, 1e-9), 3
            ),
            "bytes_quorum_overhead_x_zlib": round(
                solo_rate / max(zl_rate, 1e-9), 3
            ),
            "bytes_ship_compression_ratio": round(wire_ratio, 2),
            "bytes_ship_pipelined_batches": int(zl_st["ship_pipelined"]),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for svc in svcs:
            try:
                svc.stop()
            except OSError:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _retrieval_lane(smoke: bool) -> dict:
    """Retrieval-serving lane (ISSUE 17; EULER_BENCH_RETRIEVAL=0
    opt-out): filtered/unfiltered top-K queries/s and latency tails over
    a 2-shard fleet, the router's fan-out-vs-merge split, and the
    standing `retrieval_bit_parity` oracle — every measured answer is
    also checked bit-for-bit against the single-process NumPy reference,
    so a throughput number from a wrong answer can never land on the
    artifact."""
    from euler_tpu.retrieval import EmbeddingCorpus, numpy_topk_oracle
    from euler_tpu.retrieval.client import RetrievalClient
    from euler_tpu.retrieval.server import RetrievalServer

    n, dim, queries, k = (300, 16, 40, 8) if smoke else (20_000, 64, 300, 32)
    rng = np.random.default_rng(17)
    ids = np.sort(
        rng.choice(max(10 * n, 1000), size=n, replace=False).astype(np.uint64)
    )
    vecs = rng.standard_normal((n, dim)).astype(np.float32)
    attrs = {"cat": rng.integers(0, 4, size=n)}
    corpus = EmbeddingCorpus.build(ids, vecs, attrs=attrs, metric="cosine")
    dnf = [[("cat", "in", [0, 2])]]
    mask = np.isin(np.asarray(attrs["cat"]), [0, 2])
    servers, shard_addrs = [], []
    cli = None
    try:
        for part in range(2):
            srv = RetrievalServer(
                corpus=corpus, part=part, num_parts=2, warm_k=k
            ).start()
            servers.append(srv)
            shard_addrs.append([(srv.host, srv.port)])
        cli = RetrievalClient(shard_addrs)
        qs = rng.standard_normal((queries, 4, dim)).astype(np.float32)
        parity = True

        def measure(use_dnf):
            nonlocal parity
            lat = []
            cli.retrieve(qs[0], k, dnf=dnf if use_dnf else None)  # warm
            for q in qs:
                t1 = time.perf_counter()
                got = cli.retrieve(q, k, dnf=dnf if use_dnf else None)
                lat.append((time.perf_counter() - t1) * 1e3)
                # oracle check OUTSIDE the timed span: throughput must
                # not price the referee in
                want = numpy_topk_oracle(
                    ids, vecs, q, k, metric="cosine",
                    mask=mask if use_dnf else None,
                )
                parity = parity and all(
                    np.array_equal(np.asarray(g), np.asarray(w))
                    for g, w in zip(got, want)
                )
            total = sum(lat) / 1e3
            lat = np.sort(np.asarray(lat))
            return (
                queries / total,
                float(lat[len(lat) // 2]),
                float(lat[min(len(lat) - 1, int(len(lat) * 0.99))]),
            )

        qps, p50, p99 = measure(False)
        fqps, _, _ = measure(True)
        rst = cli.router.stats()
        busy = rst["fanout_s"] + rst["merge_s"]
        return {
            "retrieval": True,
            "retrieval_rows": n,
            "retrieval_queries_per_sec": round(qps, 1),
            "retrieval_p50_ms": round(p50, 3),
            "retrieval_p99_ms": round(p99, 3),
            "retrieval_filtered_over_unfiltered": round(
                fqps / max(qps, 1e-9), 3
            ),
            "retrieval_merge_overhead_pct": round(
                100.0 * rst["merge_s"] / max(busy, 1e-9), 2
            ),
            "retrieval_bit_parity": bool(parity),
        }
    finally:
        if cli is not None:
            cli.close()
        for srv in servers:
            srv.stop()


def _reshard_lane(smoke: bool) -> dict:
    """Elastic-reshard lane (ISSUE 19; EULER_BENCH_RESHARD=0 opt-out):
    what a live 2 -> 3 shard split costs on the artifact — pure
    repartition throughput (rows/s through `repartition_arrays`), the
    coordinator's fence-to-commit cutover window, the writer-OBSERVED
    write-unavailability gap (a client hammering single-row upserts
    straight through the cutover, fence absorption + topology-watch
    re-route included), and the `reshard_bit_parity` oracle — the
    resharded cluster must hash identically to a from-scratch build of
    exactly the acked mutations at the new shard count."""
    import shutil
    import tempfile
    import threading

    from euler_tpu.distributed import connect
    from euler_tpu.distributed.registry import Registry
    from euler_tpu.distributed.reshard import (
        ReshardCoordinator, cluster_signature, repartition_arrays,
    )
    from euler_tpu.distributed.service import GraphService
    from euler_tpu.distributed.writer import GraphWriter
    from euler_tpu.graph import Graph
    from euler_tpu.graph.builder import build_from_json

    n = 300 if smoke else 3000
    rng = np.random.default_rng(29)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=8).tolist()}]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": (s + off) % n + 1, "type": 0,
         "weight": float(1 + (s + off) % 3), "features": []}
        for s in range(1, n + 1)
        for off in (1, 5)
    ]
    # canonical edge order: bit parity with a from-scratch build is
    # defined over the canonically-ordered equivalent graph.json
    edges.sort(key=lambda e: (e["src"], e["dst"], e["type"]))
    data = {"nodes": nodes, "edges": edges}

    # pure repartition throughput, no wire involved
    meta_b, parts_b = build_from_json(data, 2)
    t0 = time.perf_counter()
    repartition_arrays(meta_b, parts_b, 3)
    repart_s = time.perf_counter() - t0
    rows_per_sec = (len(nodes) + len(edges)) / max(repart_s, 1e-9)

    tmp = tempfile.mkdtemp(prefix="etpu_bench_reshard_")
    reg = os.path.join(tmp, "reg")
    old_refresh = os.environ.get("EULER_TPU_TOPOLOGY_REFRESH_S")
    os.environ["EULER_TPU_TOPOLOGY_REFRESH_S"] = "0.2"
    svcs, g, writer, co = [], None, None, None
    try:
        src = Graph.from_json(data, num_partitions=2)
        for s in range(2):
            svcs.append(
                GraphService(
                    src.shards[s], src.meta, s,
                    registry=Registry(reg, ttl=10.0),
                    wal_dir=os.path.join(tmp, f"wal_{s}"),
                ).start()
            )
        g = connect(registry_path=reg, num_shards=2)
        writer = GraphWriter(g)

        # acked-write timeline straight through the cutover: the max
        # inter-ack gap IS the client-observed unavailability window
        acked: dict = {}
        stop = threading.Event()
        fail: list = []

        def hammer():
            try:
                i = 0
                stamps = [time.perf_counter()]
                while not stop.is_set():
                    s = int(rng.integers(1, n + 1))
                    d = int(rng.integers(1, n + 1))
                    w = float(i % 7 + 1)
                    writer.upsert_edges([s], [d], [0], [w])
                    writer.flush()
                    acked[(s, d, 0)] = w
                    stamps.append(time.perf_counter())
                    i += 1
                acked["_stamps"] = stamps
            except Exception as e:  # noqa: BLE001
                fail.append(repr(e))

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        co = ReshardCoordinator(reg, 2, 3, os.path.join(tmp, "rs"))
        report = co.run()
        stop.set()
        th.join(timeout=60)
        if fail or report.get("outcome") != "done":
            raise RuntimeError(f"reshard failed: {fail or report}")
        stamps = acked.pop("_stamps")
        gaps = np.diff(np.asarray(stamps))
        unavail_ms = float(gaps.max()) * 1e3 if len(gaps) else 0.0
        writer.publish()
        writer.close()

        # oracle: base + the acked upserts, from scratch at 3 shards
        by_key = {(e["src"], e["dst"], e["type"]): e for e in data["edges"]}
        for (s, d, t), w in acked.items():
            if (s, d, t) in by_key:
                by_key[(s, d, t)]["weight"] = w
            else:
                data["edges"].append(
                    {"src": s, "dst": d, "type": t, "weight": w,
                     "features": []}
                )
                by_key[(s, d, t)] = data["edges"][-1]
        for proc in co._dest_procs:
            proc.kill()
            proc.wait(timeout=10)
        gen1 = os.path.join(tmp, "rs", "gen_1")
        from euler_tpu.graph import format as tformat
        from euler_tpu.graph import wal as _wal
        from euler_tpu.graph.meta import GraphMeta as _Meta
        from euler_tpu.graph.store import GraphStore as _Store

        meta_r = _Meta.load(os.path.join(gen1, "data"))
        parts_r = []
        for p in range(3):
            arrays = tformat.read_arrays(
                os.path.join(gen1, "data", f"part_{p}"), mmap=False
            )
            rec = _wal.recover(
                meta_r, p, os.path.join(gen1, f"wal_{p}"),
                _Store(meta_r, arrays, p),
            )
            parts_r.append(rec.store.arrays)
        parity = cluster_signature(meta_r, parts_r) == cluster_signature(
            *build_from_json(data, 3)
        )
        return {
            "reshard": True,
            "reshard_bit_parity": bool(parity),
            "reshard_rows_per_sec": round(rows_per_sec, 1),
            "reshard_cutover_ms": round(float(report["cutover_ms"]), 1),
            "reshard_unavail_ms": round(unavail_ms, 1),
        }
    finally:
        if old_refresh is None:
            os.environ.pop("EULER_TPU_TOPOLOGY_REFRESH_S", None)
        else:
            os.environ["EULER_TPU_TOPOLOGY_REFRESH_S"] = old_refresh
        if g is not None:
            g.stop_topology_watch()
        if co is not None:
            for proc in co._dest_procs:
                try:
                    proc.kill()
                except (OSError, ProcessLookupError):
                    pass
        for svc in svcs:
            try:
                svc.stop()
            except OSError:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _resume_lane(smoke: bool) -> dict:
    """Durable-training lane (ISSUE 10; EULER_BENCH_RESUME=0 opt-out):
    checkpoint cost on the step path with the async writer vs inline
    sync commits (the save-cadence vs step-time tradeoff SCALE.md
    documents), resume-to-first-step latency, retained-checkpoint disk
    footprint, and the `resume_bit_parity` oracle — train 2N straight vs
    train N + fresh-process restore + N, params and per-step losses
    bit-identical under the standing seed contract."""
    import shutil
    import tempfile

    import jax

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.estimator import Estimator, EstimatorConfig
    from euler_tpu.graph import Graph
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.training import (
        CheckpointStore,
        SessionConfig,
        TrainingSession,
        resumable_node_batches,
    )

    n, feat_dim, dims, half, cadence = (
        (48, 8, [16, 16], 8, 4) if smoke else (400, 32, [64, 64], 24, 8)
    )
    rng = np.random.default_rng(11)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [
             {"name": "feat", "type": "dense",
              "value": rng.normal(size=feat_dim).tolist()},
             {"name": "label", "type": "dense",
              "value": [1.0, 0.0] if i % 2 else [0.0, 1.0]},
         ]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": (s + d) % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for s in range(1, n + 1)
        for d in (1, 2, 3)
    ]
    graph = Graph.from_json({"nodes": nodes, "edges": edges})
    model = GraphSAGESupervised(dims=dims, label_dim=2)
    tmp = tempfile.mkdtemp(prefix="etpu_bench_resume_")

    def make(subdir: str, async_save: bool):
        flow = FullNeighborDataFlow(
            graph, ["feat"], num_hops=len(dims), max_degree=4,
            label_feature="label",
        )
        source = resumable_node_batches(graph, flow, 16, seed=5)
        est = Estimator(
            model, source,
            EstimatorConfig(
                model_dir=os.path.join(tmp, subdir), log_steps=10**9
            ),
        )
        return TrainingSession(
            est, source=source, graph=graph,
            cfg=SessionConfig(
                checkpoint_every=cadence, async_save=async_save,
                anomaly_policy="off",
            ),
        )

    try:
        # step-path checkpoint stall: inline sync commit vs host-snapshot
        # + background writer (same cadence, same state size)
        s_sync = make("sync", async_save=False)
        s_sync.run(2 * half)
        t_sync = s_sync.telemetry
        sync_ms = t_sync["save_stall_ms_total"] / max(t_sync["saves"], 1)

        s_straight = make("straight", async_save=True)
        rep_a = s_straight.run(2 * half)
        t_async = s_straight.telemetry
        async_ms = (
            t_async["save_stall_ms_total"] / max(t_async["saves"], 1)
        )

        # the kill/resume half: fresh session objects over the same
        # model_dir = everything a dead process would have lost
        s_b1 = make("resumed", async_save=True)
        s_b1.run(half)
        s_b2 = make("resumed", async_save=True)
        t0 = time.perf_counter()
        s_b2.restore()
        s_b2.run(1)
        resume_first_ms = (time.perf_counter() - t0) * 1e3
        rep_b = s_b2.run(half - 1)

        la = jax.tree_util.tree_leaves(s_straight.est.params)
        lb = jax.tree_util.tree_leaves(s_b2.est.params)
        parity = all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(la, lb)
        ) and rep_a["losses"][half + 1:] == rep_b["losses"]

        store = CheckpointStore(os.path.join(tmp, "straight"))
        ckpt_bytes = 0
        for step in store.steps():
            d = store._path(step)
            ckpt_bytes += sum(
                os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)
            )
        return {
            "resume": True,
            "resume_save_sync_ms": round(sync_ms, 3),
            "resume_save_async_stall_ms": round(async_ms, 3),
            "resume_to_first_step_ms": round(resume_first_ms, 2),
            "resume_ckpt_bytes": int(ckpt_bytes),
            "resume_retained_ckpts": len(store.steps()),
            "resume_bit_parity": bool(parity),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _analytics_lane(smoke: bool) -> dict:
    """Whole-graph analytics lane (ISSUE 12; EULER_BENCH_ANALYTICS=0
    opt-out): PageRank BSP sweep rate over the 2-shard engine, frontier
    exchange bytes, the incremental-vs-full recompute speedup after a
    live publish, and the `analytics_bit_parity` oracle — 1-shard and
    2-shard runs (and the incremental rerun) must agree bit-for-bit."""
    from euler_tpu.analytics import (
        WholeGraphEngine,
        pagerank,
        rerun_incremental,
    )
    from euler_tpu.distributed.writer import GraphWriter
    from euler_tpu.graph import Graph

    n = 300 if smoke else 3000
    nodes = [
        {"id": i, "type": 0, "weight": 1.0, "features": []}
        for i in range(1, n + 1)
    ]
    edges = [
        {"src": s, "dst": (s + off) % n + 1, "type": off % 2,
         "weight": float(1 + (s + off) % 4), "features": []}
        for s in range(1, n + 1)
        for off in (1, 3, 7)
    ]
    data = {"nodes": nodes, "edges": edges}
    g2 = Graph.from_json(data, num_partitions=2)
    eng = WholeGraphEngine(g2)
    t0 = time.perf_counter()
    r2 = pagerank(g2, engine=eng, max_iters=50, tol=1e-10)
    sweep_s = time.perf_counter() - t0
    r1 = pagerank(Graph.from_json(data, num_partitions=1), max_iters=50,
                  tol=1e-10)
    parity = np.array_equal(
        r1.by_id()[1].view(np.uint64), r2.by_id()[1].view(np.uint64)
    )
    # live publish, then incremental replay vs from-scratch at the new
    # epoch — parity extends to the rerun, speedup is wall-clock
    w = GraphWriter(g2)
    w.upsert_edges([5, 9], [12, max(n // 2, 13)], [0, 1], [9.0, 3.5])
    pub = w.publish()
    t0 = time.perf_counter()
    r_full = pagerank(g2, max_iters=50, tol=1e-10)
    t_full = time.perf_counter() - t0
    t0 = time.perf_counter()
    r_inc = rerun_incremental(g2, r2, publish=pub, engine=eng)
    t_inc = time.perf_counter() - t0
    parity = parity and np.array_equal(
        r_full.values.view(np.uint64), r_inc.values.view(np.uint64)
    )
    return {
        "analytics": True,
        "analytics_bit_parity": bool(parity),
        "analytics_pagerank_sweeps_per_sec": round(
            r2.iterations / max(sweep_s, 1e-9), 2
        ),
        "analytics_exchange_bytes": int(r2.stats["exchange_bytes"]),
        "analytics_incremental_speedup_x": round(
            t_full / max(t_inc, 1e-9), 2
        ),
        "analytics_rows_recomputed_ratio": round(
            r_inc.stats["rows_recomputed"]
            / max(r_full.stats["rows_recomputed"], 1),
            4,
        ),
    }


def _dr_lane(smoke: bool) -> dict:
    """Disaster-recovery lane (ISSUE 15; EULER_BENCH_DR=0 opt-out):
    epoch-consistent backup throughput, total-loss restore-to-first-read
    latency, at-rest scrub throughput and its interference with a live
    reader, and the `dr_bit_parity` oracle — the restored cluster must
    be bit-identical to the one that was archived."""
    import shutil
    import tempfile
    import threading

    from euler_tpu.distributed.service import GraphService
    from euler_tpu.graph import Graph
    from euler_tpu.graph import backup as bk
    from euler_tpu.graph import wal as walmod

    n, batches, rows_per = (60, 24, 64) if smoke else (2000, 120, 256)
    rng = np.random.default_rng(23)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0,
         "features": [{"name": "feat", "type": "dense",
                       "value": rng.normal(size=8).tolist()}]}
        for i in range(n)
    ]
    edges = [
        {"src": s, "dst": s % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for s in range(1, n + 1)
    ]
    data = {"nodes": nodes, "edges": edges}
    tmp = tempfile.mkdtemp(prefix="etpu_bench_dr_")

    def tree_bytes(root: str) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                total += os.path.getsize(os.path.join(dirpath, f))
        return total

    svc = None
    # deterministic capture: snapshot explicitly mid-stream instead of
    # letting the background cadence thread race the archive step
    old_snap_every = os.environ.get("EULER_TPU_SNAPSHOT_EVERY")
    os.environ["EULER_TPU_SNAPSHOT_EVERY"] = "0"
    try:
        wal_root = os.path.join(tmp, "wal")
        g = Graph.from_json(data, num_partitions=1)
        svc = GraphService(
            g.shards[0], g.meta, 0,
            wal_dir=os.path.join(wal_root, "shard_0"),
        )
        r = np.random.default_rng(7)
        for b in range(batches):
            src = r.integers(1, n + 1, rows_per).astype(np.uint64)
            dst = r.integers(1, n + 1, rows_per).astype(np.uint64)
            svc.dispatch("upsert_edges", [
                f"dr:{b}", src, dst, np.zeros(rows_per, np.int32),
                r.random(rows_per).astype(np.float32),
                np.empty(0, np.uint64), np.empty(0, np.uint64),
                np.empty(0, np.int32), np.empty(0, np.float32),
            ])
            if b % 6 == 5:
                svc.dispatch("publish_epoch", [f"dr:pub:{b}"])
            if b == batches // 2:
                # mixed archive anchor: committed snapshot + WAL suffix
                assert svc.snapshot_now()
        svc.dispatch("publish_epoch", ["dr:pub:final"])
        live = {k: np.array(v) for k, v in svc.store.arrays.items()}
        live_epoch = svc.store.graph_epoch

        # backup throughput over the durable footprint it archives
        arch = os.path.join(tmp, "arch")
        t0 = time.perf_counter()
        bk.backup_cluster(bk.collect_shard_dirs(wal_root), arch)
        backup_s = time.perf_counter() - t0
        arch_mb = tree_bytes(arch) / 1e6

        # total loss: the cluster's durable state is gone; restore, boot
        # a fresh service on the materialized dirs (ctor auto-recovers),
        # and serve a first read
        svc.stop()
        svc = None
        shutil.rmtree(wal_root)
        g2 = Graph.from_json(data, num_partitions=1)
        t0 = time.perf_counter()
        bk.restore_cluster(arch, wal_root)
        svc = GraphService(
            g2.shards[0], g2.meta, 0,
            wal_dir=os.path.join(wal_root, "shard_0"),
        )
        svc.store.get_dense_feature(
            np.arange(1, min(n, 64) + 1, dtype=np.uint64), ["feat"]
        )
        restore_ms = (time.perf_counter() - t0) * 1e3
        parity = (
            svc.store.graph_epoch == live_epoch
            and set(live) == set(svc.store.arrays)
            and all(
                np.array_equal(np.asarray(svc.store.arrays[k]), live[k])
                for k in live
            )
        )

        # at-rest scrub throughput over snapshots + WAL on the restored
        # shard, then back-to-back passes looping in the background while
        # a reader hammers the store — the WORST-CASE interference ratio
        # SCALE.md quotes (a real deployment scrubs on EULER_TPU_SCRUB_S
        # cadence, so the amortized cost scales with the duty cycle)
        shard_dir = os.path.join(wal_root, "shard_0")
        t0 = time.perf_counter()
        rep = svc.scrub_now()
        scrub_s = time.perf_counter() - t0
        scrubbed_mb = (
            rep["wal_bytes_checked"]
            + sum(
                tree_bytes(os.path.join(shard_dir, d))
                for d in os.listdir(shard_dir)
                if walmod.is_committed_snapshot_name(d)
            )
        ) / 1e6

        ids = np.arange(1, min(n, 64) + 1, dtype=np.uint64)

        def read_rate(seconds: float) -> float:
            count, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < seconds:
                svc.store.get_dense_feature(ids, ["feat"])
                count += 1
            return count / (time.perf_counter() - t0)

        window = 0.3 if smoke else 1.0
        idle_rate = read_rate(window)
        stop = threading.Event()

        def scrub_loop():
            while not stop.is_set():
                svc.scrub_now()

        t = threading.Thread(target=scrub_loop, daemon=True)
        t.start()
        try:
            busy_rate = read_rate(window)
        finally:
            stop.set()
            t.join(timeout=10)
        return {
            "dr": True,
            "dr_bit_parity": bool(parity),
            "dr_backup_mb_per_sec": round(
                arch_mb / max(backup_s, 1e-9), 2
            ),
            "dr_archive_mb": round(arch_mb, 3),
            "dr_restore_to_first_read_ms": round(restore_ms, 2),
            "dr_scrub_mb_per_sec": round(
                scrubbed_mb / max(scrub_s, 1e-9), 2
            ),
            "dr_read_rate_scrub_over_idle": round(
                busy_rate / max(idle_rate, 1e-9), 3
            ),
        }
    finally:
        if old_snap_every is None:
            os.environ.pop("EULER_TPU_SNAPSHOT_EVERY", None)
        else:
            os.environ["EULER_TPU_SNAPSHOT_EVERY"] = old_snap_every
        if svc is not None:
            svc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def run(platform: str) -> tuple[float, dict]:
    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.datasets.synthetic import random_graph

    on_cpu = platform == "cpu"
    # EULER_BENCH_DEVICE_FLOW=1/0 forces the sampling path; by default
    # sampling runs on device on an accelerator but stays on the host on
    # CPU, where "device" sampling would just serialize with model compute
    # on the same cores (measured: host 2.99M vs traced 2.18M edges/s on
    # the 1-core fallback box). --smoke also defaults to the device flow
    # so the production-default path stays smoke-covered.
    env_df = os.environ.get("EULER_BENCH_DEVICE_FLOW")
    _df_default = (env_df != "0") if env_df is not None else (
        SMOKE or not on_cpu
    )
    if SMOKE:
        num_nodes, out_degree, feat_dim = 2000, 10, 16
        batch_size, fanouts, dims = 64, [5, 5], [32, 32]
        warmup, steps, steps_per_call = 2, 8, 2
    elif on_cpu:
        # fallback sizing: finish in minutes on host cores, still a real run
        num_nodes, out_degree, feat_dim = 50_000, 15, 64
        batch_size, fanouts, dims = 512, [10, 10], [128, 128]
        warmup, steps, steps_per_call = 4, 12, 4
    else:
        # the tunneled chip pays a network round trip per dispatch, so K
        # optimizer steps ride one lax.scan dispatch (steps_per_call) and
        # batch 1024 keeps the MXU matmuls large; the metric is absolute
        # edges/s vs the fixed 2M north star, not an A/B of configs
        # enough measured calls (30) that steady-state host sampling, not
        # the prefetch queue's head start, dominates the window.
        # EULER_BENCH_FEAT_DIM / EULER_BENCH_DIMS override the model
        # widths for A/B runs (e.g. the wide-F Pallas validation:
        # DIMS=256,256 with EULER_TPU_PALLAS=off vs =pallas).
        num_nodes, out_degree = 200_000, 15
        feat_dim = int(os.environ.get("EULER_BENCH_FEAT_DIM", 64))
        dims = [
            int(x)
            for x in os.environ.get("EULER_BENCH_DIMS", "128,128").split(",")
        ]
        # batch 1024 is the round-comparable headline config;
        # EULER_BENCH_BATCH raises it for max-throughput rows (the
        # device-flow step is dispatch/gather-overhead dominated at 1024,
        # so more rows per step lift edges/s until HBM or pad waste bites)
        batch_size = int(os.environ.get("EULER_BENCH_BATCH", 1024))
        fanouts = [10, 10]
        # EULER_BENCH_STEPS_PER_CALL: scan depth per dispatch — the lever
        # that amortizes the tunnel's per-dispatch round trip. Measured
        # sweep on chip (artifacts/tpu_extras_r5): device flow 30.0M@16 →
        # 37.4M@32 → 38.4M@64 edges/s, so the device-flow default is 64;
        # the host path keeps 16 (its per-step host sampling cost sits
        # outside the scan, so depth buys nothing there).
        env_k = os.environ.get("EULER_BENCH_STEPS_PER_CALL")
        steps_per_call = int(env_k) if env_k else (64 if _df_default else 16)
        warmup, steps = 2 * steps_per_call, 30 * steps_per_call

    rng = np.random.default_rng(0)
    graph = random_graph(
        num_nodes=num_nodes, out_degree=out_degree, feat_dim=feat_dim, seed=0
    )
    # round-trip through the on-disk shard format so the C++ engine serves
    # the hot sampling path (falls back to numpy if the toolchain is absent)
    native = False
    try:
        import tempfile

        from euler_tpu.graph import Graph
        from euler_tpu.graph import format as tformat

        d = tempfile.mkdtemp(prefix="etpu_bench_")
        tformat.write_arrays(os.path.join(d, "part_0"), graph.shards[0].arrays)
        graph.meta.save(d)
        graph = Graph.load(d, native=True)
        from euler_tpu.graph.native import NativeGraphStore

        native = isinstance(graph.shards[0], NativeGraphStore)
    except Exception as e:
        print(f"# native engine unavailable ({e}); using numpy store", file=sys.stderr)
    # features live in HBM (DeviceFeatureCache); batches ship int32 rows
    from euler_tpu.estimator import DeviceFeatureCache

    cache = DeviceFeatureCache(graph, ["feat"])
    bf16 = BF16 or (not on_cpu and "--fp32" not in sys.argv)

    # device flow: adjacency lives in HBM next to the features and the
    # only per-step input is a PRNG key (see _df_default above)
    device_flow = _df_default
    if device_flow:
        from euler_tpu.dataflow import DeviceSageFlow

        batch_fn = DeviceSageFlow(
            graph, fanouts=fanouts, batch_size=batch_size,
            label_feature="label",
        )
    else:
        # lean wire: ship int32 rows + labels only; edge ids, masks, and
        # the (uniform) weights are rebuilt on device — ~3x fewer H2D bytes
        flow = SageDataFlow(
            graph, ["feat"], fanouts=fanouts, label_feature="label", rng=rng,
            feature_mode="rows", lean=True,
        )

        # fresh Generator per call because batch_fn runs on prefetch
        # producer threads (a shared Generator would race); seeded from an
        # atomic counter so the root stream is reproducible run-to-run
        import itertools

        _root_seq = itertools.count()

        def batch_fn():
            root_rng = np.random.default_rng(
                np.random.SeedSequence([17, next(_root_seq)])
            )
            roots = graph.sample_node(batch_size, rng=root_rng)
            return (flow.query(roots),)

    value, _ = _measure_training(
        batch_fn, cache, dims, batch_size, fanouts,
        warmup, steps, steps_per_call, bf16, "/tmp/euler_tpu_bench",
    )
    extra = {"backend": platform + ("-fallback" if CPU_FALLBACK else ""),
             "native_engine": bool(native), "bf16": bool(bf16),
             "steps_per_call": steps_per_call, "device_flow": device_flow,
             "batch_size": batch_size}
    # paged vs dense device-lane A/B on a skewed weighted graph
    # (EULER_BENCH_PAGED=0 opt-out) — the lane the bench-contract test
    # gates: `paged` must not silently vanish from the artifact
    if os.environ.get("EULER_BENCH_PAGED", "1") != "0":
        try:
            extra.update(_paged_device_ab(SMOKE))
        except Exception as e:  # the A/B must never void the headline
            import traceback

            traceback.print_exc()
            extra.update({"paged": False, "paged_error": repr(e)[:300]})
    # streaming-mutation lane (ISSUE 8) — writer throughput, publish
    # latency, read recovery, and the merged==from-scratch parity oracle
    if os.environ.get("EULER_BENCH_MUTATION", "1") != "0":
        try:
            extra.update(_mutation_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update({"mutation": False, "mutation_error": repr(e)[:300]})
    # durability lane (ISSUE 9) — acked-writes/s fsync A/B, snapshot
    # cost, crash→recovered-first-read, recovered bit-parity oracle
    if os.environ.get("EULER_BENCH_DURABILITY", "1") != "0":
        try:
            extra.update(_durability_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update(
                {"durability": False, "durability_error": repr(e)[:300]}
            )
    # availability lane (ISSUE 13) — quorum/async/solo acked-rows/s,
    # failover write-unavailability window, follower catch-up MB/s, and
    # the caught-up follower == primary bit-parity oracle
    if os.environ.get("EULER_BENCH_AVAILABILITY", "1") != "0":
        try:
            extra.update(_availability_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update(
                {"availability": False,
                 "availability_error": repr(e)[:300]}
            )
    # durable-training resume lane (ISSUE 10) — save-stall sync vs async,
    # resume-to-first-step latency, retained-ckpt bytes, bit-parity oracle
    if os.environ.get("EULER_BENCH_RESUME", "1") != "0":
        try:
            extra.update(_resume_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update({"resume": False, "resume_error": repr(e)[:300]})
    # whole-graph analytics lane (ISSUE 12) — PageRank sweep rate,
    # exchange bytes, incremental-vs-full speedup, bit-parity oracle
    if os.environ.get("EULER_BENCH_ANALYTICS", "1") != "0":
        try:
            extra.update(_analytics_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update(
                {"analytics": False, "analytics_error": repr(e)[:300]}
            )
    # disaster-recovery lane (ISSUE 15) — backup MB/s, total-loss
    # restore-to-first-read, scrub MB/s + reader interference, bit parity
    if os.environ.get("EULER_BENCH_DR", "1") != "0":
        try:
            extra.update(_dr_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update({"dr": False, "dr_error": repr(e)[:300]})
    # byte-path lane (ISSUE 16) — dense wire f32/bf16/int8 A/B, varint
    # neighbor planes, compressed+pipelined catch-up vs identity lockstep
    if os.environ.get("EULER_BENCH_BYTES", "1") != "0":
        try:
            extra.update(_bytes_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update({"bytes": False, "bytes_error": repr(e)[:300]})
    # retrieval-serving lane (ISSUE 17) — fleet top-K queries/s, latency
    # tails, merge overhead, and the bitwise parity oracle
    if os.environ.get("EULER_BENCH_RETRIEVAL", "1") != "0":
        try:
            extra.update(_retrieval_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update(
                {"retrieval": False, "retrieval_error": repr(e)[:300]}
            )
    # elastic-reshard lane (ISSUE 19) — repartition rows/s, cutover
    # window, writer-observed unavailability, bit-parity oracle
    if os.environ.get("EULER_BENCH_RESHARD", "1") != "0":
        try:
            extra.update(_reshard_lane(SMOKE))
        except Exception as e:  # the lane must never void the headline
            import traceback

            traceback.print_exc()
            extra.update(
                {"reshard": False, "reshard_error": repr(e)[:300]}
            )
    probe = _probe_meta()
    if probe:
        extra["probe"] = probe
    return value, extra


def run_serving(platform: str) -> tuple[float, dict]:
    """The online-serving lane (ISSUE 2): a ModelServer over a trained
    checkpoint, hammered by concurrent clients through the wire protocol.
    Reports steady-state request throughput as the headline value, with
    p50/p99 request latency and `batches_per_100_requests` — the measured
    coalescing ratio of the micro-batcher (100 = no coalescing at all;
    the whole point of serving on an accelerator is driving it far below
    that)."""
    import tempfile
    import threading

    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.estimator import Estimator, EstimatorConfig, node_batches
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import InferenceRuntime, ModelServer, ServingClient

    on_cpu = platform == "cpu"
    if SMOKE:
        num_nodes, feat_dim, dims = 2000, 16, [32, 32]
        fanouts, bucket, ids_per_req = [5, 5], 32, 8
        clients, reqs_per_client = 8, 6
    elif on_cpu:
        num_nodes, feat_dim, dims = 20_000, 64, [128, 128]
        fanouts, bucket, ids_per_req = [10, 10], 64, 16
        clients, reqs_per_client = 8, 25
    else:
        num_nodes, feat_dim, dims = 200_000, 64, [128, 128]
        fanouts, bucket, ids_per_req = [10, 10], 128, 16
        clients, reqs_per_client = 16, 50
    graph = random_graph(
        num_nodes=num_nodes, out_degree=10, feat_dim=feat_dim, seed=3
    )
    flow = SageDataFlow(
        graph, ["feat"], fanouts=fanouts, label_feature="label",
        rng=np.random.default_rng(5),
    )
    model = GraphSAGESupervised(dims=dims, label_dim=2)
    cfg = EstimatorConfig(
        model_dir=tempfile.mkdtemp(prefix="etpu_serve_bench_"),
        log_steps=10**9,
    )
    est = Estimator(
        model, node_batches(graph, flow, bucket, rng=np.random.default_rng(7)),
        cfg,
    )
    est.train(total_steps=1, log=False)  # a real (if brief) checkpoint
    runtime = InferenceRuntime(model, flow, cfg, buckets=(bucket,))
    runtime.warmup()
    server = ModelServer(runtime, max_wait_us=2000).start()
    latencies_ms: list[list[float]] = [[] for _ in range(clients)]
    errors: list = []

    def worker(k: int):
        client = ServingClient((server.host, server.port))
        rng = np.random.default_rng(100 + k)
        try:
            for _ in range(reqs_per_client):
                ids = rng.integers(
                    1, num_nodes + 1, size=ids_per_req
                ).astype(np.uint64)
                t0 = time.perf_counter()
                client.predict(ids)
                latencies_ms[k].append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # lane must report, not die
            errors.append(repr(e)[:200])
        finally:
            client.close()

    try:
        # warm the serving path end to end once before timing
        probe = ServingClient((server.host, server.port))
        probe.predict(np.arange(1, ids_per_req + 1, dtype=np.uint64))
        threads = [
            threading.Thread(target=worker, args=(k,))
            for k in range(clients)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t_start
        stats = probe.stats()
        probe.close()
    finally:
        server.stop()
    lat = np.asarray([x for chunk in latencies_ms for x in chunk])
    if errors or len(lat) == 0:
        raise RuntimeError(f"serving lane failed: {errors[:3]}")
    total = len(lat)
    extra = {
        "backend": platform + ("-fallback" if CPU_FALLBACK else ""),
        "p50_ms": round(float(np.percentile(lat, 50)), 2),
        "p99_ms": round(float(np.percentile(lat, 99)), 2),
        "batches_per_100_requests": round(
            100.0 * stats["batches"] / max(stats["requests"], 1), 1
        ),
        "requests": total,
        "clients": clients,
        "ids_per_request": ids_per_req,
        "bucket": bucket,
        "max_wait_us": stats["max_wait_us"],
        "rejected_overload": stats["rejected_overload"],
        "rejected_deadline": stats["rejected_deadline"],
    }
    return total / elapsed, extra


def _emit_serving(value: float, extra: dict) -> None:
    emit(
        value, extra,
        metric="gnn_serving_requests_per_sec",
        unit="req/s",
        baseline=None,
    )


def run_recovery(platform: str) -> tuple[float, dict]:
    """The recovery lane (ISSUE 4): time-to-first-successful-batch after a
    seeded replica kill, plus the steady-state overhead of the
    deadline/retry plumbing (envelope on vs off on the same stream — must
    stay within noise, or the remote lane just paid for robustness).

    A 1-shard x 2-replica in-process cluster is enough: the lane measures
    failover latency and client-side plumbing cost, not graph throughput
    (the remote leg owns that)."""
    import tempfile

    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.distributed import (
        Fault,
        FaultPlan,
        chaos,
        connect,
        serve_shard,
    )
    from euler_tpu.graph import format as tformat

    num_nodes = 2000 if SMOKE else 20_000
    batch, steps = (32, 6) if SMOKE else (256, 20)
    g = random_graph(
        num_nodes=num_nodes, out_degree=10, feat_dim=16, seed=9
    )
    d = tempfile.mkdtemp(prefix="etpu_recovery_")
    tformat.write_arrays(os.path.join(d, "part_0"), g.shards[0].arrays)
    g.meta.save(d)
    s_a = serve_shard(d, 0, native=False)
    s_b = serve_shard(d, 0, native=False)
    try:
        remote = connect(
            cluster={
                0: [("127.0.0.1", s_a.port), ("127.0.0.1", s_b.port)]
            }
        )
        shard = remote.shards[0]
        shard.QUARANTINE_S = 0.5
        flow = SageDataFlow(
            remote, ["feat"], fanouts=[10], label_feature="label",
            rng=np.random.default_rng(0), feature_mode="rows", lean=True,
        )

        def measure(n):
            t0 = time.perf_counter()
            for _ in range(n):
                flow.minibatch(batch)
            return (time.perf_counter() - t0) / n * 1e3  # ms/batch

        measure(3)  # warm sockets + caches
        per_batch_on_ms = measure(steps)  # deadline envelope on (default)
        shard._deadline_wire = False
        per_batch_off_ms = measure(steps)  # plain ops: pre-PR-4 wire
        shard._deadline_wire = True
        overhead_pct = (
            (per_batch_on_ms - per_batch_off_ms)
            / max(per_batch_off_ms, 1e-9) * 100.0
        )

        # seeded replica kill: replica A resets on every touch from now
        # on; the NEXT batch must fail over inside the deadline
        retries_before = shard.retry_count
        chaos.install(
            FaultPlan(
                [Fault(site="client", kind="reset",
                       replica=("127.0.0.1", s_a.port))],
                seed=1,
            )
        )
        try:
            t0 = time.perf_counter()
            flow.minibatch(batch)
            ttfb_ms = (time.perf_counter() - t0) * 1e3
            post_kill_ms = measure(steps)  # steady state on the survivor
        finally:
            chaos.uninstall()
        extra = {
            "backend": platform + ("-fallback" if CPU_FALLBACK else ""),
            "per_batch_ms": round(per_batch_on_ms, 3),
            "per_batch_ms_no_deadline_wire": round(per_batch_off_ms, 3),
            "deadline_wire_overhead_pct": round(overhead_pct, 2),
            "post_kill_per_batch_ms": round(post_kill_ms, 3),
            "failover_retries": shard.retry_count - retries_before,
            "rpc_count": shard.rpc_count,
        }
        return ttfb_ms, extra
    finally:
        s_a.stop()
        s_b.stop()


def _emit_recovery(value: float, extra: dict) -> None:
    emit(
        value, extra,
        metric="rpc_recovery_time_to_first_batch_ms",
        unit="ms",
        baseline=None,
    )


def run_fleet(platform: str) -> tuple[float, dict]:
    """The serving-fleet lane (ISSUE 7): 4 replicated ModelServers behind
    a consistent-hash ServingRouter, hammered by concurrent closed-loop
    clients. Reports aggregate fleet req/s as the headline, plus:

      fleet_scaling_4x — aggregate req/s at 4 replicas over 1 replica.
        Replicas are in-process (device steps release the GIL), so the
        ratio reflects real parallel headroom: ~4x needs >= 4 cores, and
        `fleet_cores` records what this host could physically show.
      hedged_p99_ms / unhedged_p99_ms — p99 with one seeded straggler
        replica (chaos `server delay` on its predict dispatch) with and
        without budget-capped hedging; hedge telemetry proves the hedges
        stayed inside the token bucket.
      reload_parity — zero-downtime hot reload of the same checkpoint on
        one replica, canary rows bit-identical pre/post swap through the
        live batcher.
    """
    import tempfile
    import threading

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.distributed import Fault, FaultPlan, chaos
    from euler_tpu.estimator import (
        Estimator,
        EstimatorConfig,
        id_batches,
        node_batches,
    )
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import (
        InferenceRuntime,
        ModelServer,
        ServingClient,
        ServingRouter,
    )

    replicas = 4
    if SMOKE:
        num_nodes, feat_dim, dims = 2000, 16, [16, 16]
        bucket, ids_per_req = 16, 16
        clients, reqs = 8, 16
        straggler_reqs = 10
    else:
        num_nodes, feat_dim, dims = 8000, 32, [32, 32]
        bucket, ids_per_req = 32, 32
        clients, reqs = 12, 30
        straggler_reqs = 16
    straggler_delay_s = 0.25
    graph = random_graph(
        num_nodes=num_nodes, out_degree=8, feat_dim=feat_dim, seed=11
    )

    def mkflow():
        # deterministic per root: the precondition for the hedged ==
        # unhedged == offline-infer bit-parity claim
        return FullNeighborDataFlow(
            graph, ["feat"], num_hops=2, max_degree=6, label_feature="label"
        )

    flow = mkflow()
    model = GraphSAGESupervised(dims=dims, label_dim=2)
    cfg = EstimatorConfig(
        model_dir=tempfile.mkdtemp(prefix="etpu_fleet_bench_"),
        log_steps=10**9,
    )
    est = Estimator(
        model,
        node_batches(graph, flow, bucket, rng=np.random.default_rng(13)),
        cfg,
    )
    est.train(total_steps=1, log=False)  # a real (if brief) checkpoint

    servers = []
    for i in range(replicas):
        runtime = InferenceRuntime(model, mkflow(), cfg, buckets=(bucket,))
        runtime.warmup()
        servers.append(ModelServer(runtime, max_wait_us=2000, shard=i).start())
    addrs = [(s.host, s.port) for s in servers]

    def hammer(client, n_clients, n_reqs, seed0):
        lats = [[] for _ in range(n_clients)]
        errors: list = []

        def worker(k):
            rng = np.random.default_rng(
                np.random.SeedSequence([17, seed0, k])
            )
            try:
                for _ in range(n_reqs):
                    ids = rng.integers(
                        1, num_nodes + 1, size=ids_per_req
                    ).astype(np.uint64)
                    t0 = time.perf_counter()
                    client.predict(ids)
                    lats[k].append((time.perf_counter() - t0) * 1e3)
            except Exception as e:  # lane must report, not die
                errors.append(repr(e)[:200])

        threads = [
            threading.Thread(target=worker, args=(k,))
            for k in range(n_clients)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t_start
        lat = np.asarray([x for chunk in lats for x in chunk])
        if errors or len(lat) == 0:
            raise RuntimeError(f"fleet lane failed: {errors[:3]}")
        return len(lat) / elapsed, lat

    try:
        # bit-parity anchor: routed predictions == offline Estimator.infer
        probe_ids = np.arange(1, min(num_nodes, 64) + 1, dtype=np.uint64)
        batches, chunks = id_batches(flow, probe_ids, bucket)
        _, direct = est.infer(batches, chunks)
        parity_client = ServingClient(addrs, routing="consistent_hash")
        routed = parity_client.predict(probe_ids)
        bit_parity = bool(np.array_equal(routed, direct))
        parity_client.close()

        # warm each replica's wire + flow path once before timing
        for addr in addrs:
            w = ServingClient(addr)
            w.predict(np.arange(1, ids_per_req + 1, dtype=np.uint64))
            w.close()

        # ---- scaling: 1 replica vs 4 replicas, hedging off so the
        # ratio measures routing spread, not duplicate hedge load
        solo_client = ServingClient(
            [addrs[0]],
            routing=ServingRouter([addrs[0]], hedge=False),
        )
        solo_rps, _ = hammer(solo_client, clients, reqs, seed0=1)
        solo_client.close()
        fleet_client = ServingClient(
            addrs,
            routing=ServingRouter(
                addrs, policy="consistent_hash", hedge=False
            ),
        )
        fleet_rps, fleet_lat = hammer(fleet_client, clients, reqs, seed0=2)
        fleet_client.close()

        # ---- hedging under one seeded straggler replica: the chaos
        # `server delay` fault stalls every predict dispatched on the
        # last replica; consistent-hash routing keeps sending ~1/4 of
        # requests into it, so the unhedged p99 IS the straggler
        chaos.install(FaultPlan([
            Fault(site="server", kind="delay", op="predict",
                  shard=replicas - 1, delay_s=straggler_delay_s),
        ], seed=23))
        try:
            unhedged = ServingRouter(
                addrs, policy="consistent_hash", hedge=False
            )
            unhedged_client = ServingClient(addrs, routing=unhedged)
            _, unhedged_lat = hammer(
                unhedged_client, clients, straggler_reqs, seed0=3
            )
            unhedged_client.close()
            # pinned hedge delay (the EULER_TPU_HEDGE_MS shape): with a
            # SEEDED straggler owning ~1/4 of the traffic, the p95 of
            # observed latencies converges onto the straggler itself, so
            # the adaptive delay is the wrong tool for this measurement
            hedged = ServingRouter(
                addrs, policy="consistent_hash", hedge=True,
                hedge_ms=straggler_delay_s * 1e3 * 0.25,
            )
            hedge_cap = hedged._hedge_budget.cap
            hedged_client = ServingClient(addrs, routing=hedged)
            _, hedged_lat = hammer(
                hedged_client, clients, straggler_reqs, seed0=4
            )
            hstats = hedged.stats()
            hedged_client.close()
        finally:
            chaos.uninstall()

        # within-budget proof: every hedge spent a token the bucket
        # could cover (cap + refill-per-success), and none were denied
        # by a dry bucket mid-measurement
        hedged_within_budget = bool(
            hstats["hedges"]
            <= hedge_cap + 0.5 * max(hstats["requests"], 1)
        )

        # ---- zero-downtime hot reload: same checkpoint back in, canary
        # rows through the live batcher must be bit-identical pre/post
        reload_client = ServingClient(addrs[0])
        report = reload_client.reload(
            canary_ids=probe_ids[: min(len(probe_ids), bucket)]
        )
        reload_client.close()
        reload_parity = bool(
            all(
                r.get("canary_parity") is True
                for r in report.values()
            )
        )

        unhedged_p99 = float(np.percentile(unhedged_lat, 99))
        hedged_p99 = float(np.percentile(hedged_lat, 99))
        extra = {
            "backend": platform + ("-fallback" if CPU_FALLBACK else ""),
            "replicas": replicas,
            "fleet_cores": os.cpu_count() or 1,
            "routing": "consistent_hash",
            "fleet_req_per_sec": round(fleet_rps, 1),
            "solo_req_per_sec": round(solo_rps, 1),
            "fleet_scaling_4x": round(fleet_rps / max(solo_rps, 1e-9), 3),
            "fleet_p50_ms": round(float(np.percentile(fleet_lat, 50)), 2),
            "fleet_p99_ms": round(float(np.percentile(fleet_lat, 99)), 2),
            "straggler_delay_ms": round(straggler_delay_s * 1e3, 1),
            "unhedged_p99_ms": round(unhedged_p99, 2),
            "hedged_p99_ms": round(hedged_p99, 2),
            "hedge_p99_cut": round(
                unhedged_p99 / max(hedged_p99, 1e-9), 3
            ),
            "hedges_issued": int(hstats["hedges"]),
            "hedges_won": int(hstats["hedges_won"]),
            "hedges_denied": int(hstats["hedges_denied"]),
            "hedge_budget_cap": hedge_cap,
            "hedged_within_budget": hedged_within_budget,
            "reload_parity": reload_parity,
            "fleet_bit_parity": bit_parity,
            "clients": clients,
            "ids_per_request": ids_per_req,
            "bucket": bucket,
        }
        return fleet_rps, extra
    finally:
        for s in servers:
            s.stop()


def _emit_fleet(value: float, extra: dict) -> None:
    emit(
        value, extra,
        metric="gnn_fleet_requests_per_sec",
        unit="req/s",
        baseline=None,
    )


_DATASET_GEN_V = 2  # bump when the synthetic generator changes, so cached
# /tmp datasets from older generator code are never silently reused


def _build_remote_dataset(
    num_nodes, out_degree, feat_dim, shards, weighted=False
) -> str:
    """Materialize (once) a sharded on-disk graph for the remote bench."""
    import tempfile

    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.graph import format as tformat

    d = os.path.join(
        tempfile.gettempdir(),
        f"etpu_rbench_v{_DATASET_GEN_V}"
        f"_{num_nodes}_{out_degree}_{feat_dim}_{shards}"
        + ("_w" if weighted else ""),
    )
    if os.path.exists(os.path.join(d, "euler.meta.json")):
        return d
    t0 = time.time()
    g = random_graph(
        num_nodes=num_nodes,
        out_degree=out_degree,
        feat_dim=feat_dim,
        num_partitions=shards,
        seed=0,
        weighted=weighted,
    )
    # build in a temp dir and rename into place: a kill mid-build (driver
    # timeout / watchdog os._exit) must not leave a half-written dataset
    # behind the cache marker — that would poison every later bench run
    # at this deterministic /tmp path
    import shutil

    tmp_d = d + ".build"
    if os.path.exists(tmp_d):
        shutil.rmtree(tmp_d)
    os.makedirs(tmp_d)
    for p, sh in enumerate(g.shards):
        tformat.write_arrays(os.path.join(tmp_d, f"part_{p}"), sh.arrays)
    g.meta.save(tmp_d)
    # a stale dir without the marker (pre-atomic-build kill) blocks the
    # rename; clear it. If a concurrent run renamed a COMPLETE dataset in
    # meanwhile, keep theirs.
    if os.path.exists(d) and not os.path.exists(
        os.path.join(d, "euler.meta.json")
    ):
        shutil.rmtree(d)
    try:
        os.rename(tmp_d, d)
    except OSError:
        if not os.path.exists(os.path.join(d, "euler.meta.json")):
            raise
        shutil.rmtree(tmp_d)
    print(
        f"# remote bench dataset built: {num_nodes} nodes x{out_degree}"
        f" deg, {shards} shards ({time.time() - t0:.0f}s)",
        file=sys.stderr,
    )
    return d


def run_remote(platform: str) -> tuple[float, dict]:
    """The distributed north-star leg: GraphService processes (native
    engine inside) serve a sharded graph over the socket protocol; the
    trainer pulls fused one-RPC minibatches (server-side root sampling +
    multi-hop fanout + labels) while training on the chip.

    This is the reference's core deployment (remote_op.cc:60-120,
    grpc_worker.cc:40-96): graph engine in separate processes, trainer a
    pure client.
    """
    import subprocess
    import tempfile

    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.distributed import Registry, connect
    from euler_tpu.estimator import DeviceFeatureCache
    from euler_tpu.graph import Graph

    on_cpu = platform == "cpu"
    shards = int(os.environ.get("EULER_BENCH_REMOTE_SHARDS", 2))
    if SMOKE:
        num_nodes, out_degree, feat_dim = 2000, 10, 16
        batch_size, fanouts, dims = 64, [5, 5], [32, 32]
        warmup, steps, steps_per_call = 2, 8, 2
    elif on_cpu:
        num_nodes, out_degree, feat_dim = 50_000, 10, 64
        batch_size, fanouts, dims = 512, [10, 10], [128, 128]
        warmup, steps, steps_per_call = 4, 12, 4
    else:
        # >=20M edges served remotely (VERDICT r2 #1); 1M nodes keeps the
        # device feature cache to ~130MB bf16 so staging over the tunneled
        # chip stays well under transport limits. 480 steps = 30 measured
        # scan calls, same window rule as the local leg: steady-state
        # host/RPC sampling, not the prefetch queue's head start, must
        # dominate what is being claimed.
        num_nodes, out_degree, feat_dim = 1_000_000, 20, 64
        batch_size, fanouts, dims = 1024, [10, 10], [128, 128]
        # 48-step warmup = 3 scan calls: the tunneled chip's dispatch path
        # takes a couple of calls to reach steady state
        warmup, steps, steps_per_call = 48, 480, 16

    leg_t0 = time.monotonic()

    def note(msg):
        print(f"# remote[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr)
        sys.stderr.flush()

    # EULER_BENCH_WEIGHTED=1: non-unit edge weights → the weighted-lean
    # wire (bf16 weights next to the rows) instead of the unit-lean wire
    weighted = os.environ.get("EULER_BENCH_WEIGHTED", "0") == "1"
    data = _build_remote_dataset(
        num_nodes, out_degree, feat_dim, shards, weighted=weighted
    )
    reg = tempfile.mkdtemp(prefix="etpu_rbench_reg_")
    global _REMOTE_PROCS
    procs = _REMOTE_PROCS = [
        subprocess.Popen(
            [
                sys.executable, "-m", "euler_tpu.distributed.service",
                "--data", data, "--shard", str(i), "--registry", reg,
            ]
            + (["--no-native"] if SMOKE else []),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        for i in range(shards)
    ]
    try:
        cluster = Registry(reg).wait_for(
            shards, timeout=min(120.0, REMOTE_BUDGET_S / 2)
        )
        remote = connect(cluster=cluster)
        note(f"{shards} shard servers up")
        # the device feature cache bootstraps from the local mmap of the
        # same shard files (a one-time deployment step — trainers stream
        # or mount the feature table once); per-batch traffic afterwards
        # is int32 rows only
        local = Graph.load(data, native=False)
        import jax.numpy as _jnp

        cache = DeviceFeatureCache(
            local,
            ["feat"],
            dtype=_jnp.bfloat16 if not on_cpu else _jnp.float32,
            stage_chunk_rows=250_000,
        )
        import jax as _jax

        _jax.block_until_ready(cache.table)
        note(f"feature cache staged ({cache.table.nbytes >> 20}MB)")
        rng = np.random.default_rng(0)
        flow = SageDataFlow(
            remote, ["feat"], fanouts=fanouts, label_feature="label",
            rng=rng, feature_mode="rows", lean=True,
        )
        bf16 = not on_cpu

        # overlapped one-RPC minibatches (EULER_BENCH_INFLIGHT outstanding
        # requests per shard) — the async completion-queue client parity
        inflight = int(os.environ.get("EULER_BENCH_INFLIGHT", "4"))
        # the per-shard executor must be at least as deep as the request
        # window, or the recorded "inflight" would overstate true overlap
        os.environ.setdefault("EULER_TPU_INFLIGHT", str(inflight))
        if inflight > 1:
            from euler_tpu.estimator import pipelined_batches

            batch_fn = pipelined_batches(flow, batch_size, depth=inflight)
        else:
            def batch_fn():
                return (flow.minibatch(batch_size),)

        note("warmup + measure")
        value, _ = _measure_training(
            batch_fn, cache, dims, batch_size, fanouts,
            warmup, steps, steps_per_call, bf16, "/tmp/euler_tpu_rbench",
        )
        if flow._lean_off:
            raise RuntimeError(
                "remote lean wire downgraded during the run — fix before"
                " trusting the number"
            )

        # ---- planner RPC-count lane: measure (not assert) the L×P → P
        # reduction of the fused SPLIT→exec_plan→MERGE fanout vs the
        # per-op per-hop path, on the same roots/config ----
        from euler_tpu.query.plan import plan_mode

        probe_batches = 4
        probe_roots = remote.sample_node(
            batch_size, rng=np.random.default_rng(11)
        )

        def _plan_probe(mode: str) -> tuple[float, float]:
            prev = os.environ.get("EULER_TPU_FUSED_PLAN")
            os.environ["EULER_TPU_FUSED_PLAN"] = mode
            try:
                before = sum(sh.rpc_count for sh in remote.shards)
                t0 = time.perf_counter()
                for k in range(probe_batches):
                    remote.fanout_with_rows(
                        probe_roots, None, fanouts,
                        rng=np.random.default_rng(100 + k),
                    )
                dt = time.perf_counter() - t0
                rpcs = sum(sh.rpc_count for sh in remote.shards) - before
                return rpcs / probe_batches, dt / probe_batches
            finally:
                if prev is None:
                    os.environ.pop("EULER_TPU_FUSED_PLAN", None)
                else:
                    os.environ["EULER_TPU_FUSED_PLAN"] = prev

        fused_rpcs, fused_s = _plan_probe("1")
        perop_rpcs, perop_s = _plan_probe("0")
        note(
            f"plan lane: fused {fused_rpcs:.1f} rpc/batch"
            f" ({fused_s * 1e3:.0f}ms) vs per-op {perop_rpcs:.1f}"
            f" ({perop_s * 1e3:.0f}ms)"
        )

        # ---- client read-cache lane (EULER_BENCH_CACHE=0 opt-out): the
        # dense-feature remote SAGE path, measured uncached (kill switch)
        # vs warm-cache on the SAME roots and seeds. Warm batches serve
        # hot feature rows client-side and dedup ids before the wire —
        # the repeated-hot-node regime every power-law graph lives in.
        # Results are bit-identical across all three passes (the cached
        # lane's standing contract, pinned by tests/test_read_cache.py).
        cache_extra = {}
        if os.environ.get("EULER_BENCH_CACHE", "1") != "0":
            from euler_tpu.distributed.cache import (
                GATHER_DEDUP,
                clear_graph_caches,
                graph_cache_stats,
            )

            gd_before = dict(GATHER_DEDUP)

            ab_batches = 2 if SMOKE else 4
            dense_flow = SageDataFlow(
                remote, ["feat"], fanouts=fanouts, label_feature="label",
                rng=np.random.default_rng(31), feature_mode="dense",
            )
            ab_roots = [
                remote.sample_node(
                    batch_size, rng=np.random.default_rng(300 + i)
                )
                for i in range(ab_batches)
            ]

            def ab_pass():
                dense_flow.rng = np.random.default_rng(77)
                t0 = time.perf_counter()
                for r in ab_roots:
                    dense_flow.query(r)
                return time.perf_counter() - t0

            saved = [sh._cache for sh in remote.shards]
            for sh in remote.shards:
                sh._cache = None
            uncached_s = ab_pass()
            for sh, c in zip(remote.shards, saved):
                sh._cache = c
            clear_graph_caches(remote)
            cold_s = ab_pass()  # miss pass: dedup + write-back only
            warm_s = ab_pass()  # same roots/seeds → hot rows hit
            st = graph_cache_stats(remote) or {}
            edges_ab = 0
            width = batch_size
            for k in fanouts:
                edges_ab += width * k
                width *= k
            edges_ab *= ab_batches
            # dedup savings = cache-layer residual dedup + the dataflow
            # layer's cross-hop unique-ID coalescing (gather_unique)
            dedup_saved = int(st.get("dedup_bytes_saved", 0)) + (
                GATHER_DEDUP["bytes_saved"] - gd_before["bytes_saved"]
            )
            cache_extra = {
                "cache_hit_rate": st.get("hit_rate", 0.0),
                "dedup_bytes_saved": dedup_saved,
                "cache_bytes_saved": int(st.get("bytes_saved", 0)),
                "cache_uncached_edges_per_sec": round(edges_ab / uncached_s, 1),
                "cache_cold_edges_per_sec": round(edges_ab / cold_s, 1),
                "cache_warm_edges_per_sec": round(edges_ab / warm_s, 1),
                "cache_warm_over_uncached": round(uncached_s / warm_s, 3),
            }
            note(
                f"cache lane: warm {uncached_s / warm_s:.2f}x uncached"
                f" (hit rate {st.get('hit_rate', 0.0):.2f},"
                f" dedup saved {dedup_saved >> 20}MB)"
            )

        # ---- paged device sub-lane (EULER_BENCH_PAGED=0 opt-out): stage
        # the ragged paged adjacency FROM THE REMOTE CLUSTER over the wire
        # (ids_by_rows + get_full_neighbor sweeps, deterministic verbs →
        # read-cache-served on repeats), then sample fully on device —
        # zero wire bytes per step — and drive residual feature-row
        # re-fetches through the ReadCache-backed double-buffer ring.
        def _paged_remote_lane() -> dict:
            import jax as _jx

            from euler_tpu.dataflow import DeviceSageFlow
            from euler_tpu.estimator import ResidualFetchRing

            t0 = time.perf_counter()
            dflow = DeviceSageFlow(
                remote, fanouts=fanouts, batch_size=batch_size,
                label_feature="label", layout="paged",
            )
            stage_s = time.perf_counter() - t0
            fn = _jx.jit(dflow.sample)
            _jx.block_until_ready(
                _jx.tree_util.tree_leaves(fn(_jx.random.PRNGKey(0)))
            )
            reps = 4 if SMOKE else 20
            t0 = time.perf_counter()
            out = None
            for t in range(reps):
                out = fn(_jx.random.PRNGKey(1 + t))
            _jx.block_until_ready(_jx.tree_util.tree_leaves(out))
            dt = time.perf_counter() - t0
            eps_step = 0
            width = batch_size
            for k in fanouts:
                eps_step += width * k
                width *= k
            ring = ResidualFetchRing(cache, remote)
            try:
                rows = np.arange(min(4096, num_nodes), dtype=np.int64)
                for _ in range(2):  # pass 1 fills the read cache, 2 hits
                    ring.prefetch(rows)
                    ring.flush()
                rst = ring.stats()
            finally:
                ring.close()
            note(
                f"paged device lane: staged in {stage_s:.1f}s,"
                f" {reps * eps_step / dt:.0f} edges/s on-device,"
                f" residual hit rate {rst['residual_fetch_hit_rate']:.2f}"
            )
            return {
                "device_flow": True,
                "paged": True,
                "paged_stage_s": round(stage_s, 2),
                "paged_device_edges_per_sec": round(
                    reps * eps_step / dt, 1
                ),
                "residual_fetch_hit_rate": rst["residual_fetch_hit_rate"],
                "residual_rows_refetched": rst["fetched_rows"],
            }

        paged_extra = {}
        if os.environ.get("EULER_BENCH_PAGED", "1") != "0":
            if time.monotonic() - leg_t0 > REMOTE_BUDGET_S * 0.5:
                # never let the sub-lane push the leg past the watchdog
                paged_extra = {"paged": False, "paged_skipped": "budget"}
            else:
                try:
                    paged_extra = _paged_remote_lane()
                except Exception as e:  # must never void the remote number
                    import traceback

                    traceback.print_exc()
                    paged_extra = {
                        "paged": False, "paged_error": repr(e)[:300],
                    }
        extra = {
            "backend": platform,
            "shards": shards,
            "server_processes": shards,
            "edges_total": num_nodes * out_degree,
            "steps_per_call": steps_per_call,
            "bf16": bool(bf16),
            "weighted_lean": bool(weighted),
            "inflight": inflight,
            "remote_fused": plan_mode() == "fused",
            "remote_rpcs_per_batch": round(fused_rpcs, 2),
            "remote_rpcs_per_batch_per_op": round(perop_rpcs, 2),
            "remote_plan_ms_fused": round(fused_s * 1e3, 1),
            "remote_plan_ms_per_op": round(perop_s * 1e3, 1),
            **cache_extra,
            **paged_extra,
        }
        probe = _probe_meta()
        if probe:
            extra["probe"] = probe
        return value, extra
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def _emit_remote(value: float, extra: dict) -> None:
    emit(value, extra, metric="graphsage_remote_edges_per_sec_per_chip")


def main():
    try:
        platform = warm_backend()
    except Exception as e:  # even backend bring-up failure emits the line
        emit(0.0, {"backend": "none", "error": repr(e)[:300]})
        return
    remote_enabled = os.environ.get("EULER_BENCH_REMOTE", "1") != "0"
    serving_enabled = os.environ.get("EULER_BENCH_SERVING", "1") != "0"
    recovery_enabled = os.environ.get("EULER_BENCH_RECOVERY", "1") != "0"
    fleet_enabled = os.environ.get("EULER_BENCH_FLEET", "1") != "0"

    # ---- fleet-only mode: just the serving-fleet lane (its own JSON
    # contract line), for the fleet gate in tests/test_bench_contract.py
    if "--fleet-only" in sys.argv:
        try:
            f_value, f_extra = run_fleet(platform)
            _emit_fleet(f_value, f_extra)
        except Exception as e:
            import traceback

            traceback.print_exc()
            _emit_fleet(0.0, {"backend": platform, "error": repr(e)[:300]})
        return

    # ---- LOCAL leg first: the headline artifact is emitted before the
    # remote leg can spend a second of the driver's timeout (VERDICT r3 #1).
    value, extra = None, {}
    if "--remote-only" not in sys.argv:
        try:
            value, extra = run(platform)
        except Exception as e:
            import traceback

            traceback.print_exc()
            value, extra = 0.0, {"backend": platform, "error": repr(e)[:300]}
        emit(value, extra)

    # ---- SERVING lane: in-process server + concurrent wire clients.
    # Cheap relative to the legs (seconds of requests against a tiny
    # checkpoint), and emitted immediately like the local leg so a later
    # timeout can't void it.
    if serving_enabled and "--remote-only" not in sys.argv:
        try:
            s_value, s_extra = run_serving(platform)
            _emit_serving(s_value, s_extra)
            extra = dict(
                extra,
                serving_requests_per_sec=round(float(s_value), 1),
                serving_p50_ms=s_extra["p50_ms"],
                serving_p99_ms=s_extra["p99_ms"],
                serving_batches_per_100_requests=s_extra[
                    "batches_per_100_requests"
                ],
            )
        except Exception as e:
            import traceback

            traceback.print_exc()
            _emit_serving(0.0, {"backend": platform, "error": repr(e)[:300]})

    # ---- RECOVERY lane: seeded replica kill against a tiny in-process
    # replica pair — seconds of wall clock, emitted immediately.
    if recovery_enabled and "--remote-only" not in sys.argv:
        try:
            r_value, r_extra = run_recovery(platform)
            _emit_recovery(r_value, r_extra)
            extra = dict(
                extra,
                recovery_ttfb_ms=round(float(r_value), 1),
                recovery_deadline_wire_overhead_pct=r_extra[
                    "deadline_wire_overhead_pct"
                ],
            )
        except Exception as e:
            import traceback

            traceback.print_exc()
            _emit_recovery(
                0.0, {"backend": platform, "error": repr(e)[:300]}
            )

    # ---- FLEET lane: 4 in-process replicas behind the router, seeded
    # straggler + hedging, hot reload — seconds of wall clock, emitted
    # immediately like the lanes above.
    if fleet_enabled and "--remote-only" not in sys.argv:
        try:
            f_value, f_extra = run_fleet(platform)
            _emit_fleet(f_value, f_extra)
            extra = dict(
                extra,
                fleet_req_per_sec=round(float(f_value), 1),
                fleet_scaling_4x=f_extra["fleet_scaling_4x"],
                hedged_p99_ms=f_extra["hedged_p99_ms"],
                reload_parity=f_extra["reload_parity"],
            )
        except Exception as e:
            import traceback

            traceback.print_exc()
            _emit_fleet(0.0, {"backend": platform, "error": repr(e)[:300]})

    if not remote_enabled:
        if "--remote-only" in sys.argv:
            # never exit silently: the contract is at least one JSON line
            emit(0.0, {"error": "--remote-only with EULER_BENCH_REMOTE=0"})
        elif (
            serving_enabled or recovery_enabled or fleet_enabled
        ) and value is not None:
            # the serving lane printed after the headline; re-emit the
            # headline (serving summary attached) so BOTH first-line and
            # last-line parsers still read the local number
            emit(value, extra)
        return

    # ---- REMOTE leg under an internal wall-clock budget. The watchdog
    # force-emits partial results and exits 0 on expiry; anything already
    # printed (the local line above) is preserved.
    import threading

    done = threading.Event()

    def _watchdog():
        if done.wait(REMOTE_BUDGET_S):
            return
        _emit_remote(0.0, {
            "error": f"remote leg exceeded internal budget"
                     f" ({REMOTE_BUDGET_S:.0f}s)",
        })
        if value is not None:  # re-emit the headline as the final line
            emit(value, extra)
        for p in _REMOTE_PROCS:
            try:
                p.kill()
            except Exception:
                pass
        os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()
    try:
        remote_value, remote_extra = run_remote(platform)
        _emit_remote(remote_value, remote_extra)
    except Exception as e:
        import traceback

        traceback.print_exc()
        _emit_remote(0.0, {"error": repr(e)[:300]})
        remote_value = None
    done.set()
    if "--remote-only" in sys.argv or value is None:
        return
    # final combined headline line: whichever line the driver parses (first
    # or last), it carries the verified local number
    if remote_value is not None:
        extra = dict(extra, remote_edges_per_sec=round(float(remote_value), 1))
    emit(value, extra)


if __name__ == "__main__":
    main()
