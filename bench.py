"""Headline benchmark: sampled edges/sec training GraphSAGE on one chip.

Trains supervised GraphSAGE (fanout sampling + mean-aggregator convs) on a
synthetic random graph, with host-side sampling prefetched on worker threads
overlapping the jitted device step. Metric matches the north star in
BASELINE.json: sampled edges/sec/chip (target 2M on v5e).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N/2e6}

Usage: python bench.py [--smoke]   (--smoke: tiny sizes, forced CPU)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

SMOKE = "--smoke" in sys.argv
BF16 = "--bf16" in sys.argv
BASELINE_EDGES_PER_SEC = 2_000_000.0


def main():
    if SMOKE:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.estimator import Estimator, EstimatorConfig
    from euler_tpu.estimator.prefetch import Prefetcher
    from euler_tpu.models import GraphSAGESupervised

    if SMOKE:
        num_nodes, out_degree, feat_dim = 2000, 10, 16
        batch_size, fanouts, dims = 64, [5, 5], [32, 32]
        warmup, steps = 2, 8
    else:
        # batch 1024 amortizes per-step dispatch latency; the metric is
        # absolute edges/s vs the fixed 2M north star, not an A/B of configs
        num_nodes, out_degree, feat_dim = 200_000, 15, 64
        batch_size, fanouts, dims = 1024, [10, 10], [128, 128]
        warmup, steps = 5, 30

    rng = np.random.default_rng(0)
    graph = random_graph(
        num_nodes=num_nodes, out_degree=out_degree, feat_dim=feat_dim, seed=0
    )
    # round-trip through the on-disk shard format so the C++ engine serves
    # the hot sampling path (falls back to numpy if the toolchain is absent)
    try:
        import os
        import tempfile

        from euler_tpu.graph import Graph
        from euler_tpu.graph import format as tformat

        d = tempfile.mkdtemp(prefix="etpu_bench_")
        tformat.write_arrays(os.path.join(d, "part_0"), graph.shards[0].arrays)
        graph.meta.save(d)
        graph = Graph.load(d, native=True)
    except Exception as e:
        print(f"# native engine unavailable ({e}); using numpy store", file=sys.stderr)
    # features live in HBM (DeviceFeatureCache); batches ship int32 rows
    from euler_tpu.estimator import DeviceFeatureCache

    cache = DeviceFeatureCache(graph, ["feat"])
    flow = SageDataFlow(
        graph, ["feat"], fanouts=fanouts, label_feature="label", rng=rng,
        feature_mode="rows", lazy_blocks=True,
    )
    conv_kwargs = None
    if BF16:
        import jax.numpy as jnp

        conv_kwargs = {"dtype": jnp.bfloat16}
    model = GraphSAGESupervised(dims=dims, label_dim=2, conv_kwargs=conv_kwargs)

    def batch_fn():
        roots = graph.sample_node(batch_size, rng=np.random.default_rng())
        return (flow.query(roots),)

    # workers stage batches onto the device so H2D overlaps compute
    prefetch = Prefetcher(batch_fn, depth=6, workers=4, device_put=True)
    est = Estimator(
        model,
        prefetch,
        EstimatorConfig(
            model_dir="/tmp/euler_tpu_bench",
            learning_rate=0.01,
            log_steps=10**9,
        ),
        feature_cache=cache,
    )

    # edges sampled per step: every hop's sample_neighbor draws
    edges_per_step = 0
    width = batch_size
    for k in fanouts:
        edges_per_step += width * k
        width *= k

    est.train(total_steps=warmup, log=False, save=False)  # compile + warm
    t0 = time.perf_counter()
    est.train(total_steps=steps, log=False, save=False)
    jax.block_until_ready(est.params)
    dt = time.perf_counter() - t0
    prefetch.close()

    value = steps * edges_per_step / dt
    print(
        json.dumps(
            {
                "metric": "graphsage_sampled_edges_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "edges/s",
                "vs_baseline": round(value / BASELINE_EDGES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
